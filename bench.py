"""Headline benchmark: GPT-2 training throughput on one chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

vs_baseline is measured MFU / 0.40, the BASELINE.md north-star target
(GPT-2 ≥40% MFU; see BASELINE.md "Targets for the TPU-native build").
On a TPU chip this runs GPT-2-small @ seq 1024 in bf16 with the Pallas
flash-attention kernel; off-TPU (CI) it falls back to a tiny config so the
harness still produces a line.
"""
import dataclasses
import glob
import hashlib
import json
import os
import sys
import time


from ray_tpu._private.tpu_probe import tpu_reachable_once as _tpu_reachable_once

# Timestamped probe-attempt audit trail; surfaces in the JSON "extra" so a
# CPU-fallback artifact documents WHEN the tunnel was tried and found dead.
_PROBE_LOG: list = []


def _tpu_reachable(window_s: float = None) -> bool:
    """Retry the reachability probe with backoff across a run window.

    The tunnel flakes on a scale of minutes-to-hours; one 120 s attempt
    (round 3) conflated "down right now" with "down for the round" and
    cost the round its TPU benchmark artifact. Default window 20 min,
    overridable via RAY_TPU_BENCH_PROBE_WINDOW_S (0 = single attempt).
    """
    if window_s is None:
        window_s = float(os.environ.get("RAY_TPU_BENCH_PROBE_WINDOW_S", 1200))
    deadline = time.monotonic() + window_s
    delay = 30.0
    attempt = 0
    while True:
        attempt += 1
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        if _tpu_reachable_once():
            print(f"# bench: [{stamp}] TPU probe {attempt} SUCCEEDED",
                  file=sys.stderr)
            _PROBE_LOG.append(f"{stamp} probe {attempt}: ok")
            return True
        _PROBE_LOG.append(f"{stamp} probe {attempt}: unreachable")
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            print(f"# bench: [{stamp}] TPU unreachable after {attempt} "
                  "probe(s); falling back to CPU smoke", file=sys.stderr)
            return False
        wait = min(delay, remaining)
        print(f"# bench: [{stamp}] TPU probe {attempt} failed; retrying in "
              f"{wait:.0f}s ({remaining:.0f}s left in window)",
              file=sys.stderr)
        time.sleep(wait)
        delay = min(delay * 2, 300.0)


if not os.environ.get("RAY_TPU_BENCH_SKIP_PROBE") and not _tpu_reachable():
    # Fall back to the CPU smoke config rather than hanging forever.
    # BOTH the env var and the config.update are required: the axon
    # sitecustomize overrides JAX_PLATFORMS programmatically, so the env
    # var alone is ignored (same workaround as tests/conftest.py). The
    # probe's extra jax init on healthy TPU hosts (~20-40s) is the price
    # of not wedging the whole bench run on a hung tunnel — there is no
    # cheaper reachability check through the tunnel than a backend init.
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import jax.numpy as jnp

# bf16 peak FLOP/s per chip by device kind (public numbers).
_PEAK_FLOPS = {
    "v5 lite": 197e12,  # v5e
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6 lite": 918e12,  # trillium
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in _PEAK_FLOPS.items():
        if key in kind:
            return val
    return 0.0


def _best_tpu_capture() -> dict | None:
    """Locate the best in-round TPU bench capture and fingerprint it.

    A CPU-fallback artifact cites the TPU capture it stands in for; the
    path + sha256 pair makes the provenance chain mechanical (a reviewer
    verifies the cited numbers came from exactly that file, not from a
    transcript paraphrase). Best = highest headline value among
    repo-root BENCH_TPU_*.json files whose extra.backend is "tpu".
    """
    root = os.path.dirname(os.path.abspath(__file__))
    best = None
    for path in sorted(glob.glob(os.path.join(root, "BENCH_TPU_*.json"))):
        try:
            with open(path, "rb") as f:
                raw = f.read()
            rec = json.loads(raw)
            if rec.get("extra", {}).get("backend") != "tpu":
                continue
            value = float(rec.get("value", 0))
        except Exception:
            continue   # malformed capture: skip it, never kill the bench
        if best is None or value > best["value"]:
            best = {"path": os.path.basename(path),
                    "sha256": hashlib.sha256(raw).hexdigest(),
                    "value": value,
                    "metric": rec.get("metric", "")}
    return best


def main():
    from ray_tpu.models import gpt2
    from ray_tpu.parallel.train_step import (
        default_optimizer,
        make_train_state,
        make_train_step,
    )

    on_tpu = jax.default_backend() == "tpu"
    if on_tpu:
        # remat off: with the lean LN/MLP custom VJPs (models/layers.py)
        # batch 16 fits one 16 GiB chip without checkpointing, and skipping
        # the recompute is worth ~0.06 MFU (measured 0.42 vs 0.36).
        cfg = dataclasses.replace(gpt2.gpt2_small(), remat=False)
        batch, seq, timed_steps = 16, 1024, 20
    else:
        cfg = gpt2.gpt2_tiny()
        batch, seq, timed_steps = 8, 64, 3

    opt = default_optimizer(1e-4, warmup_steps=10, total_steps=1000)
    state = make_train_state(lambda rng: gpt2.init(rng, cfg), jax.random.PRNGKey(0), opt)
    step = make_train_step(lambda p, b: gpt2.loss_fn(p, b, cfg), opt)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab_size)
    batch_data = {"tokens": tokens}

    # Warmup (compile) then timed steps. Sync by forcing the last step's loss
    # to host: states chain through donation, so the last loss being ready
    # implies every step ran. (block_until_ready on device buffers returns
    # early through the axon tunnel; a scalar fetch is a true barrier.)
    for _ in range(2):
        state, metrics = step(state, batch_data)
    float(metrics["total_loss"])
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        state, metrics = step(state, batch_data)
    float(metrics["total_loss"])
    dt = time.perf_counter() - t0

    steps_per_sec = timed_steps / dt
    tokens_per_sec = steps_per_sec * batch * seq
    # fwd+bwd FLOPs/token: 6*N_params + attention (6 * L * S * d_model,
    # causal-halved QK^T+PV fwd+bwd) — the PaLM-appendix accounting.
    flops_per_token = 6 * cfg.n_params + 6 * cfg.n_layer * seq * cfg.d_model
    peak = _peak_flops(jax.devices()[0])
    mfu = (tokens_per_sec * flops_per_token / peak) if peak else 0.0

    extra = {
        "mfu": round(mfu, 4),
        "steps_per_sec": round(steps_per_sec, 3),
        "loss": float(metrics["loss"]),
        "batch": batch,
        "seq": seq,
        "n_params": cfg.n_params,
        "backend": jax.default_backend(),
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
        "probe_log": _PROBE_LOG,
    }
    if not on_tpu:
        # CPU fallback: cite the TPU capture this artifact stands in
        # for, fingerprinted so the provenance chain is mechanical
        extra["tpu_capture"] = _best_tpu_capture()
    print(
        json.dumps(
            {
                "metric": "gpt2_small_train_tokens_per_sec_per_chip"
                if on_tpu
                else "gpt2_tiny_cpu_smoke_tokens_per_sec",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(mfu / 0.40, 4) if peak else 0.0,
                "extra": extra,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
