#!/usr/bin/env bash
# TSAN/ASAN pass over the native runtime (store, RPC core, data server).
#
# Reference analog: the asan-tagged test configs of the reference
# (python/ray/tests/BUILD asan tags). Builds the stress driver against
# the real sources with each sanitizer and runs every mode; any
# sanitizer report fails the run (halt_on_error=1).
#
# Usage: scripts/sanitize.sh [iters]   (default 2000)
set -u
cd "$(dirname "$0")/.."
ITERS="${1:-2000}"
SRC="src/stress/stress_native.cc src/store/store.cc src/store/data_server.cc src/rpc/rpc_core.cc"
OUT=build/sanitize
mkdir -p "$OUT"
fail=0

# The Client/Server handle structs leak BY DESIGN (documented in
# rpc_core.cc rpc_cl_close/rpc_sv_stop: threads may still be inside
# wait/send when close races them; the leaked struct reports "closed"
# forever instead of dangling). Suppress exactly those two allocation
# sites; every other allocation (frame buffers, queues) must be freed.
cat > "$OUT/lsan.supp" <<'SUPP'
leak:rpc_cl_connect
leak:rpc_sv_start
SUPP

for SAN in thread address; do
  BIN="$OUT/stress_$SAN"
  echo "== building -fsanitize=$SAN =="
  if ! g++ -O1 -g -std=c++17 -fsanitize=$SAN -fno-omit-frame-pointer \
       -o "$BIN" $SRC -lpthread -lrt 2> "$OUT/build_$SAN.log"; then
    echo "BUILD FAILED for $SAN (see $OUT/build_$SAN.log)"
    fail=1
    continue
  fi
  for MODE in store rpc dataserver; do
    echo "-- $SAN / $MODE --"
    if [ "$SAN" = thread ]; then
      TSAN_OPTIONS="halt_on_error=1" "$BIN" "$MODE" "$ITERS" \
          2> "$OUT/${SAN}_${MODE}.log"
    else
      ASAN_OPTIONS="halt_on_error=1:detect_leaks=1" \
      LSAN_OPTIONS="suppressions=$OUT/lsan.supp" \
          "$BIN" "$MODE" "$ITERS" 2> "$OUT/${SAN}_${MODE}.log"
    fi
    rc=$?
    tail -3 "$OUT/${SAN}_${MODE}.log"
    if [ $rc -ne 0 ]; then
      echo "FAIL: $SAN/$MODE rc=$rc (full log: $OUT/${SAN}_${MODE}.log)"
      fail=1
    fi
  done
done

if [ $fail -eq 0 ]; then
  echo "SANITIZE PASS: tsan+asan clean over store/rpc/dataserver"
fi
exit $fail
