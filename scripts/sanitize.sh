#!/usr/bin/env bash
# TSAN/ASAN pass over the native runtime (store, RPC core, data server).
#
# Reference analog: the asan-tagged test configs of the reference
# (python/ray/tests/BUILD asan tags). Builds the stress driver against
# the real sources with each sanitizer and runs every mode; any
# sanitizer report fails the run (halt_on_error=1).
#
# Usage: scripts/sanitize.sh [--smoke] [iters]
#   --smoke: quick gate mode — tsan only (the race detector, i.e. the
#            defect class this script exists for), small iteration
#            count. Run by the `slow`-marked test in
#            tests/test_zz_lint.py whenever a compiler is present, so
#            the native race gate is exercised in CI instead of dead.
#   iters:   stress iterations per mode (default 2000; smoke 100)
set -u
cd "$(dirname "$0")/.."
SANS="thread address"
SMOKE=0
if [ "${1:-}" = "--smoke" ]; then
  SMOKE=1
  SANS="thread"
  shift
fi
ITERS="${1:-$([ "$SMOKE" = 1 ] && echo 100 || echo 2000)}"
SRC="src/stress/stress_native.cc src/store/store.cc src/store/data_server.cc src/rpc/rpc_core.cc"
OUT=build/sanitize
mkdir -p "$OUT"
fail=0

# The Client/Server handle structs leak BY DESIGN (documented in
# rpc_core.cc rpc_cl_close/rpc_sv_stop: threads may still be inside
# wait/send when close races them; the leaked struct reports "closed"
# forever instead of dangling). Suppress exactly those two allocation
# sites; every other allocation (frame buffers, queues) must be freed.
# The two extra patterns cover INDIRECT leaks owned by those leaked
# roots (this lsan does not auto-suppress children of a suppressed
# root): the client's sync_waiting hashtable nodes/buckets (allocated
# in rpc_cl_send's seq insert — the only allocation that function
# makes) and the server queue deque's retained node (allocated in
# push_event; deque keeps one node even after the stop-path drain).
cat > "$OUT/lsan.supp" <<'SUPP'
leak:rpc_cl_connect
leak:rpc_sv_start
leak:rpc_cl_send
leak:push_event
SUPP

for SAN in $SANS; do
  BIN="$OUT/stress_$SAN"
  echo "== building -fsanitize=$SAN =="
  if ! g++ -O1 -g -std=c++17 -fsanitize=$SAN -fno-omit-frame-pointer \
       -o "$BIN" $SRC -lpthread -lrt 2> "$OUT/build_$SAN.log"; then
    echo "BUILD FAILED for $SAN (see $OUT/build_$SAN.log)"
    fail=1
    continue
  fi
  for MODE in store rpc dataserver; do
    echo "-- $SAN / $MODE --"
    if [ "$SAN" = thread ]; then
      TSAN_OPTIONS="halt_on_error=1" "$BIN" "$MODE" "$ITERS" \
          2> "$OUT/${SAN}_${MODE}.log"
    else
      ASAN_OPTIONS="halt_on_error=1:detect_leaks=1" \
      LSAN_OPTIONS="suppressions=$OUT/lsan.supp" \
          "$BIN" "$MODE" "$ITERS" 2> "$OUT/${SAN}_${MODE}.log"
    fi
    rc=$?
    tail -3 "$OUT/${SAN}_${MODE}.log"
    if [ $rc -ne 0 ]; then
      echo "FAIL: $SAN/$MODE rc=$rc (full log: $OUT/${SAN}_${MODE}.log)"
      fail=1
    fi
  done
done

if [ $fail -eq 0 ]; then
  if [ "$SMOKE" = 1 ]; then
    echo "SANITIZE PASS (smoke): tsan clean over store/rpc/dataserver"
  else
    echo "SANITIZE PASS: tsan+asan clean over store/rpc/dataserver"
  fi
fi
exit $fail
