"""Fused flash attention (Pallas TPU kernels, forward AND backward).

The hot op of the flagship models. Forward is a Pallas kernel: grid over
(batch*heads, Q blocks, KV blocks), online-softmax accumulators held in
VMEM scratch across the sequential KV grid dimension, causal blocks
skipped at block granularity. Backward is two Pallas kernels (the standard
flash-attention split): a dq kernel gridded (BH, Q blocks, KV blocks) and
a dk/dv kernel gridded (BH, KV blocks, Q blocks), each recomputing the
probability block from the saved logsumexp — no O(S²) tensor is ever
materialized in HBM, unlike a naive VJP.

On non-TPU backends the kernels run in Pallas interpret mode (tests) or
callers use parallel.ring_attention.reference_attention.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *, scale, causal,
    block_q, block_k, seq_len, padded,
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = ik * block_k

    def _compute():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # [block_q, block_k]
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if padded:
            # Mask KV padding columns (inputs padded up to the block size).
            s = jnp.where(cols < seq_len, s, NEG_INF)
        m_prev = m_ref[:, 0]  # [block_q]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # Skip KV blocks entirely in the future of this Q block.
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_ref[:, 0]
        o_ref[0] = (acc_ref[:] / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
        # lse is materialized as [BH, 8, S] (8 broadcast sublanes) to satisfy
        # the TPU (8, 128) block-tiling constraint; callers slice [:, 0, :].
        lse = m_ref[:, 0] + jnp.log(jnp.maximum(l, 1e-30))
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _flash_fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    """q,k,v: [BH, S, D] -> (o [BH,S,D], lse [BH,S]).

    Sequence lengths that don't divide the block size are zero-padded up to
    the next block multiple; padded KV columns are masked inside the kernel
    and padded Q rows sliced off the output.
    """
    BH, S, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    S_pad = -(-S // block_q) * block_q
    S_pad = -(-S_pad // block_k) * block_k
    if S_pad != S:
        pad = [(0, 0), (0, S_pad - S), (0, 0)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    grid = (BH, S_pad // block_q, S_pad // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        seq_len=S, padded=S_pad != S,
    )
    try:
        cparams = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    except Exception:  # older/newer param name drift
        cparams = None
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S_pad, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 8, S_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        **({"compiler_params": cparams} if cparams is not None else {}),
        interpret=interpret,
    )(q, k, v)
    return o[:, :S], lse[:, 0, :S]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(
        q, k, v, scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return o


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(
        q, k, v, scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return o, (q, k, v, o, lse)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc,
    *, scale, causal, block_q, block_k, seq_len, padded,
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    q_start = iq * block_q
    k_start = ik * block_k

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]      # [block_q]
        delta = delta_ref[0, 0]  # [block_q]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if padded:
            s = jnp.where(cols < seq_len, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                       # [bq, bk]
        dp = jax.lax.dot_general(                           # do @ v^T
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dq_acc[:] += jax.lax.dot_general(                   # ds @ k
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc, dv_acc, *, scale, causal, block_q, block_k, seq_len, padded,
):
    ikb = pl.program_id(1)   # KV block (parallel)
    iqb = pl.program_id(2)   # Q block (sequential accumulation)
    nq = pl.num_programs(2)

    @pl.when(iqb == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = iqb * block_q
    k_start = ikb * block_k

    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if padded:
            s = jnp.where(cols < seq_len, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])                       # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(                   # p^T @ do
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(                           # do @ v^T
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None]) * scale
        dk_acc[:] += jax.lax.dot_general(                   # ds^T @ q
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # Q blocks strictly before this KV block contribute nothing.
        pl.when(q_start + block_q - 1 >= k_start)(_compute)
    else:
        _compute()

    @pl.when(iqb == nq - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _broadcast8(x):
    """[BH, S] → [BH, 8, S] so the (8, 128) TPU tile constraint holds for
    row-vector inputs (same trick the forward uses for its lse output)."""
    return jnp.broadcast_to(x[:, None, :], (x.shape[0], 8, x.shape[1]))


def _flash_bwd(q, k, v, o, lse, do, *, scale, causal, block_q, block_k,
               interpret):
    """Pallas backward: returns (dq, dk, dv), each [BH, S, D]."""
    BH, S, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    S_pad = -(-S // block_q) * block_q
    S_pad = -(-S_pad // block_k) * block_k
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if S_pad != S:
        pad = [(0, 0), (0, S_pad - S), (0, 0)]
        q, k, v, o, do = (jnp.pad(x, pad) for x in (q, k, v, o, do))
        lse = jnp.pad(lse, [(0, 0), (0, S_pad - S)])
        delta = jnp.pad(delta, [(0, 0), (0, S_pad - S)])
    lse8 = _broadcast8(lse)
    delta8 = _broadcast8(delta)
    nq, nk = S_pad // block_q, S_pad // block_k
    kw = dict(scale=scale, causal=causal, block_q=block_q, block_k=block_k,
              seq_len=S, padded=S_pad != S)
    try:
        cparams = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    except Exception:
        cparams = None
    cp = {"compiler_params": cparams} if cparams is not None else {}

    qspec = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0))
    row_q = pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, **kw),
        grid=(BH, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, row_q, row_q],
        out_specs=[qspec],
        out_shape=[jax.ShapeDtypeStruct((BH, S_pad, D), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        **cp,
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)[0]

    # dk/dv: grid transposed — KV blocks parallel, Q blocks sequential
    qspec2 = pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, j, 0))
    kspec2 = pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, i, 0))
    row_q2 = pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, j))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, **kw),
        grid=(BH, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, row_q2, row_q2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct((BH, S_pad, D), k.dtype),
                   jax.ShapeDtypeStruct((BH, S_pad, D), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                        pltpu.VMEM((block_k, D), jnp.float32)],
        **cp,
        interpret=interpret,
    )(q, k, v, do, lse8, delta8)
    return dq[:, :S], dk[:, :S], dv[:, :S]


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    dq, dk, dv = _flash_bwd(
        q, k, v, o, lse, do, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret,
    )
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention over [B, S, H, D] (heads layout matching
    models/layers.apply_attention). Differentiable via custom VJP."""
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D**0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    o = _flash(
        to_bh(q), to_bh(k), to_bh(v), scale, causal, block_q, block_k, interpret
    )
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
