"""Fused flash attention (Pallas TPU kernel).

The hot op of the flagship models. Forward is a Pallas kernel: grid over
(batch*heads, Q blocks, KV blocks), online-softmax accumulators held in
VMEM scratch across the sequential KV grid dimension, causal blocks
skipped at block granularity. Backward is a custom VJP that recomputes
probabilities from the saved logsumexp (flash-style rematerialisation;
a Pallas backward kernel is tracked as a follow-up).

On non-TPU backends the kernel runs in Pallas interpret mode (tests) or
callers use parallel.ring_attention.reference_attention.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_LANES = 128


def _fwd_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref, *, scale, causal,
    block_q, block_k, seq_len, padded,
):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = iq * block_q
    k_start = ik * block_k

    def _compute():
        q = q_ref[0]  # [block_q, D]
        k = k_ref[0]  # [block_k, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        s = s * scale  # [block_q, block_k]
        cols = k_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            s = jnp.where(rows >= cols, s, NEG_INF)
        if padded:
            # Mask KV padding columns (inputs padded up to the block size).
            s = jnp.where(cols < seq_len, s, NEG_INF)
        m_prev = m_ref[:, 0]  # [block_q]
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # Skip KV blocks entirely in the future of this Q block.
        pl.when(k_start <= q_start + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_ref[:, 0]
        o_ref[0] = (acc_ref[:] / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
        # lse is materialized as [BH, 8, S] (8 broadcast sublanes) to satisfy
        # the TPU (8, 128) block-tiling constraint; callers slice [:, 0, :].
        lse = m_ref[:, 0] + jnp.log(jnp.maximum(l, 1e-30))
        lse_ref[0] = jnp.broadcast_to(lse[None, :], (8, lse.shape[0]))


def _flash_fwd(q, k, v, *, scale, causal, block_q, block_k, interpret):
    """q,k,v: [BH, S, D] -> (o [BH,S,D], lse [BH,S]).

    Sequence lengths that don't divide the block size are zero-padded up to
    the next block multiple; padded KV columns are masked inside the kernel
    and padded Q rows sliced off the output.
    """
    BH, S, D = q.shape
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    S_pad = -(-S // block_q) * block_q
    S_pad = -(-S_pad // block_k) * block_k
    if S_pad != S:
        pad = [(0, 0), (0, S_pad - S), (0, 0)]
        q, k, v = (jnp.pad(x, pad) for x in (q, k, v))
    grid = (BH, S_pad // block_q, S_pad // block_k)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        seq_len=S, padded=S_pad != S,
    )
    try:
        cparams = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
    except Exception:  # older/newer param name drift
        cparams = None
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S_pad, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 8, S_pad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        **({"compiler_params": cparams} if cparams is not None else {}),
        interpret=interpret,
    )(q, k, v)
    return o[:, :S], lse[:, 0, :S]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(
        q, k, v, scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return o


def _flash_vjp_fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(
        q, k, v, scale=scale, causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    # Recompute P from lse (no O(S^2) residual was saved), then the standard
    # flash gradient identities.
    qf, kf, vf, of, dof = (x.astype(jnp.float32) for x in (q, k, v, o, do))
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        s = jnp.where(mask[None], s, NEG_INF)
    p = jnp.exp(s - lse[:, :, None])  # [BH, Sq, Sk]
    dv = jnp.einsum("bqk,bqd->bkd", p, dof)
    dp = jnp.einsum("bqd,bkd->bqk", dof, vf)
    delta = jnp.sum(dof * of, axis=-1, keepdims=True)  # [BH, Sq, 1]
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Flash attention over [B, S, H, D] (heads layout matching
    models/layers.apply_attention). Differentiable via custom VJP."""
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D**0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def to_bh(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    o = _flash(
        to_bh(q), to_bh(k), to_bh(v), scale, causal, block_q, block_k, interpret
    )
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
