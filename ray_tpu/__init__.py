"""ray_tpu — a TPU-native distributed AI framework.

Capability-equivalent to the surveyed reference (see SURVEY.md): tasks,
actors, a shared-memory object store, placement groups and a two-level
scheduler on the runtime side; mesh-based XLA collectives, data-parallel
training, hyperparameter tuning, datasets and serving on the library side —
all designed for TPU (JAX/XLA/Pallas) from the start.
"""
from ray_tpu import exceptions  # noqa: F401
from ray_tpu._version import __version__  # noqa: F401

# Runtime API symbols re-exported lazily so that pure-compute subpackages
# (ray_tpu.parallel, ray_tpu.models, ray_tpu.ops) can be imported without
# dragging in the runtime (and vice versa).
_API_NAMES = (
    "ObjectRef",
    "ObjectRefGenerator",
    "available_resources",
    "cancel",
    "cluster_resources",
    "get",
    "get_actor",
    "get_gpu_ids",
    "get_runtime_context",
    "init",
    "is_initialized",
    "kill",
    "method",
    "nodes",
    "put",
    "remote",
    "shutdown",
    "timeline",
    "wait",
)


def __getattr__(name):
    if name in _API_NAMES:
        from ray_tpu._private import api

        return getattr(api, name)
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_API_NAMES))


__all__ = ["__version__", "exceptions", *_API_NAMES]
