"""Transformer building blocks, pure-JAX pytree style.

Every layer is a (init_fn, apply_fn) pair over plain dict pytrees; sharding
comes from logical-axis annotations resolved by ray_tpu.parallel.sharding.
Compute is bf16 by default with f32 params/accumulators (MXU-native mix).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from ray_tpu.parallel.ring_attention import reference_attention, ring_attention_local

Params = Dict[str, Any]


def _init_dense(key, shape, scale=0.02, dtype=jnp.float32):
    return (jax.random.normal(key, shape) * scale).astype(dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm(x, scale, bias, eps=1e-5):
    """LayerNorm with a memory-lean custom VJP.

    XLA's autodiff residuals for the naive f32 LN cost ~2 f32 copies of x
    per call; saving (x, mu, rstd) and recomputing x̂ in the backward cut
    GPT-2-small step time measurably on v5e (part of the 0.34→0.42 MFU fix,
    see bench.py history) and, with the lean MLP below, lets batch 16-24
    train without remat on one 16 GiB chip."""
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _layer_norm_fwd(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (x32 - mu) * rstd
    return (y * scale + bias).astype(x.dtype), (x, mu, rstd, scale)


def _layer_norm_bwd(eps, res, dy):
    x, mu, rstd, scale = res
    dy32 = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mu) * rstd
    reduce_axes = tuple(range(x.ndim - 1))
    dscale = jnp.sum(dy32 * xhat, axis=reduce_axes)
    dbias = jnp.sum(dy32, axis=reduce_axes)
    t = dy32 * scale
    dx = rstd * (
        t
        - jnp.mean(t, axis=-1, keepdims=True)
        - xhat * jnp.mean(t * xhat, axis=-1, keepdims=True)
    )
    return (dx.astype(x.dtype), dscale.astype(scale.dtype),
            dbias.astype(scale.dtype))


layer_norm.defvjp(_layer_norm_fwd, _layer_norm_bwd)


# ---------------------------------------------------------------- attention
def init_attention(key, d_model, n_head, dtype=jnp.float32):
    head_dim = d_model // n_head
    ks = jax.random.split(key, 4)
    return {
        "wq": _init_dense(ks[0], (d_model, n_head, head_dim), dtype=dtype),
        "wk": _init_dense(ks[1], (d_model, n_head, head_dim), dtype=dtype),
        "wv": _init_dense(ks[2], (d_model, n_head, head_dim), dtype=dtype),
        "wo": _init_dense(ks[3], (n_head, head_dim, d_model), dtype=dtype),
    }


ATTENTION_LOGICAL = {
    "wq": ("embed", "heads", "head_dim"),
    "wk": ("embed", "heads", "head_dim"),
    "wv": ("embed", "heads", "head_dim"),
    "wo": ("heads", "head_dim", "embed"),
}


def apply_attention(
    params: Params,
    x: jnp.ndarray,
    *,
    causal: bool = True,
    impl: str = "reference",
    sp_axis: str = "sp",
    compute_dtype=jnp.bfloat16,
):
    """x: [B, S, D] -> [B, S, D].

    impl: "reference" (plain jnp), "flash" (Pallas TPU kernel),
    "ring" (context-parallel over the ambient mesh's `sp_axis` — callable
    from inside jit with global arrays), "ring_local" (per-shard body;
    requires already running inside shard_map with sp_axis manual).
    """
    cd = compute_dtype
    q = jnp.einsum("bsd,dhk->bshk", x.astype(cd), params["wq"].astype(cd))
    k = jnp.einsum("bsd,dhk->bshk", x.astype(cd), params["wk"].astype(cd))
    v = jnp.einsum("bsd,dhk->bshk", x.astype(cd), params["wv"].astype(cd))
    if impl == "ring":
        from ray_tpu.parallel.ring_attention import ring_attention

        o = ring_attention(q, k, v, None, causal=causal, seq_axis=sp_axis)
    elif impl == "ring_local":
        o = ring_attention_local(q, k, v, axis_name=sp_axis, causal=causal)
    elif impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        o = flash_attention(q, k, v, causal=causal)
    else:
        o = reference_attention(q, k, v, causal=causal)
    out = jnp.einsum("bshk,hkd->bsd", o.astype(cd), params["wo"].astype(cd))
    return out.astype(x.dtype)


# ---------------------------------------------------------------- dense MLP
def init_mlp(key, d_model, d_ff, dtype=jnp.float32):
    k1, k2 = jax.random.split(key)
    return {
        "w1": _init_dense(k1, (d_model, d_ff), dtype=dtype),
        "b1": jnp.zeros((d_ff,), dtype),
        "w2": _init_dense(k2, (d_ff, d_model), dtype=dtype),
        "b2": jnp.zeros((d_model,), dtype),
    }


MLP_LOGICAL = {
    "w1": ("embed", "mlp"),
    "b1": ("mlp",),
    "w2": ("mlp", "embed"),
    "b2": ("embed",),
}


def _mlp_compute(x, w1, b1, w2, b2, cd):
    u = jax.lax.dot_general(
        x.astype(cd), w1.astype(cd), (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=cd,
    ) + b1.astype(cd)
    o = jax.lax.dot_general(
        jax.nn.gelu(u), w2.astype(cd), (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=cd,
    ) + b2.astype(cd)
    return o, u


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _lean_mlp(x, w1, b1, w2, b2, cd):
    """2-layer GELU MLP with a memory-lean custom VJP: the backward saves
    only (x, w1, w2, u) — u the pre-activation — and recomputes gelu/gelu′
    elementwise. XLA's default VJP keeps ~6 hidden-sized residuals per
    layer, which is what pushed GPT-2-small batch 16 out of HBM without
    remat (measured: the no-remat OOM dump showed six [L,B,S,4D] buffers)."""
    return _mlp_compute(x, w1, b1, w2, b2, cd)[0]


def _lean_mlp_fwd(x, w1, b1, w2, b2, cd):
    o, u = _mlp_compute(x, w1, b1, w2, b2, cd)
    return o, (x, w1, w2, u)


def _lean_mlp_bwd(cd, res, do):
    x, w1, w2, u = res
    do = do.astype(cd)
    g, gvjp = jax.vjp(jax.nn.gelu, u)
    nd = x.ndim - 1
    x2 = x.reshape(-1, x.shape[-1])
    do2 = do.reshape(-1, do.shape[-1])
    g2 = g.reshape(-1, g.shape[-1])
    dg = jax.lax.dot_general(             # do @ w2^T
        do, w2.astype(cd), (((nd,), (1,)), ((), ())),
        preferred_element_type=cd,
    )
    dw2 = jax.lax.dot_general(            # g^T @ do (f32 accum)
        g2, do2, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    du = gvjp(dg)[0]
    du2 = du.reshape(-1, du.shape[-1])
    dw1 = jax.lax.dot_general(            # x^T @ du (f32 accum)
        x2.astype(cd), du2, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dx = jax.lax.dot_general(             # du @ w1^T
        du, w1.astype(cd), (((nd,), (1,)), ((), ())),
        preferred_element_type=cd,
    )
    db1 = jnp.sum(du.astype(jnp.float32), axis=tuple(range(nd)))
    db2 = jnp.sum(do.astype(jnp.float32), axis=tuple(range(nd)))
    return (dx.astype(x.dtype), dw1.astype(w1.dtype), db1.astype(w1.dtype),
            dw2.astype(w2.dtype), db2.astype(w2.dtype))


_lean_mlp.defvjp(_lean_mlp_fwd, _lean_mlp_bwd)


def apply_mlp(params: Params, x, compute_dtype=jnp.bfloat16):
    out = _lean_mlp(x, params["w1"], params["b1"], params["w2"],
                    params["b2"], compute_dtype)
    return out.astype(x.dtype)


# ---------------------------------------------------------------- MoE (EP)
@dataclasses.dataclass(frozen=True)
class MoEConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25


def init_moe(key, d_model, d_ff, cfg: MoEConfig, dtype=jnp.float32):
    kg, k1, k2 = jax.random.split(key, 3)
    E = cfg.n_experts
    return {
        "wg": _init_dense(kg, (d_model, E), dtype=dtype),
        "w1": _init_dense(k1, (E, d_model, d_ff), dtype=dtype),
        "w2": _init_dense(k2, (E, d_ff, d_model), dtype=dtype),
    }


MOE_LOGICAL = {
    "wg": ("embed", None),
    "w1": ("experts", "embed", "expert_mlp"),
    "w2": ("experts", "expert_mlp", "embed"),
}


def apply_moe(params: Params, x, cfg: MoEConfig, compute_dtype=jnp.bfloat16):
    """GShard-style top-k routed MoE with capacity, dense-dispatch einsums.

    Experts (leading E dim of w1/w2) are sharded over the `ep` mesh axis;
    the dispatch/combine einsums below are exactly the contractions XLA
    turns into all_to_all over `ep` when tokens and experts live on
    different devices — expert parallelism without hand-written comms.
    Returns (output [B,S,D], aux_loss scalar).
    """
    cd = compute_dtype
    B, S, D = x.shape
    E, K = cfg.n_experts, cfg.top_k
    C = max(1, int(cfg.capacity_factor * K * B * S / E))

    logits = jnp.einsum("bsd,de->bse", x.astype(jnp.float32), params["wg"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)  # [B,S,E]
    gate_vals, gate_idx = jax.lax.top_k(probs, K)  # [B,S,K]
    # Renormalize the chosen gates.
    gate_vals = gate_vals / jnp.maximum(jnp.sum(gate_vals, -1, keepdims=True), 1e-9)

    # Load-balancing auxiliary loss (Switch-style): fraction of tokens per
    # expert × mean router prob per expert.
    me = jnp.mean(probs, axis=(0, 1))  # [E]
    ce = jnp.mean(
        jnp.sum(jax.nn.one_hot(gate_idx[..., 0], E), axis=1) / S, axis=0
    )  # top-1 token fraction per expert
    aux_loss = E * jnp.sum(me * ce)

    # Position of each (token, k) within its expert's capacity buffer.
    # Positions are assigned over the WHOLE token stream (B*S*K flattened):
    # the dispatch einsum below sums over both b and s, so a slot (e, c)
    # must be unique across the entire batch, not per row.
    onehot = jax.nn.one_hot(gate_idx, E, dtype=jnp.int32)  # [B,S,K,E]
    flat = onehot.reshape(B * S * K, E)
    pos = jnp.cumsum(flat, axis=0) - 1  # [B*S*K, E]
    pos = pos.reshape(B, S, K, E)
    in_cap = (pos < C) & (onehot > 0)
    # dispatch [B,S,E,C]: 1 where token (b,s) occupies slot c of expert e.
    disp = jnp.sum(
        jax.nn.one_hot(jnp.where(in_cap, pos, -1), C, dtype=cd)
        * onehot.astype(cd)[..., None],
        axis=2,
    )  # sum over K -> [B,S,E,C]
    gates_per_e = jnp.sum(
        gate_vals[..., None].astype(cd) * onehot.astype(cd), axis=2
    )  # [B,S,E]
    combine = disp * gates_per_e[..., None]  # weight by gate prob

    expert_in = jnp.einsum("bsec,bsd->ecd", disp, x.astype(cd))  # a2a over ep
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"].astype(cd)))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w2"].astype(cd))
    out = jnp.einsum("bsec,ecd->bsd", combine, expert_out)  # a2a back
    return out.astype(x.dtype), aux_loss
