"""GPT-2 family — the flagship model (BASELINE.md north star:
GPT-2-1.5B ≥40% MFU on v5e-64).

Pure-JAX pytree model, TPU-first: bf16 compute / f32 params, einsum-only
(MXU), `lax.scan` over layers (one compiled block), optional remat,
sharding by logical axes (parallel/sharding.py) so the same forward runs
dp/tp/sp/ep on any mesh; pipeline-parallel forward via parallel/pipeline.py.

Equivalent reference workload: Ray Train GPT-2 fine-tune
(/root/reference/release/train_tests/, BASELINE.json configs); the model
itself is new — the reference contains no model implementations, it wraps
torch. Architecture follows the public GPT-2 description (learned
positional embeddings, pre-LN blocks, GELU MLP, tied LM head).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ray_tpu.models import layers as L
from ray_tpu.parallel import sharding as sh
from ray_tpu.parallel.pipeline import gpipe, microbatch, unmicrobatch


@dataclasses.dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304  # 50257 rounded up to a 128 multiple (MXU tiling)
    max_seq: int = 1024
    n_layer: int = 12
    n_head: int = 12
    d_model: int = 768
    d_ff: int = 0  # 0 -> 4 * d_model
    moe: Optional[L.MoEConfig] = None  # if set, every block's MLP is routed
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    attention: str = "auto"  # auto | flash | reference | ring
    aux_loss_weight: float = 0.01

    @property
    def ff(self) -> int:
        return self.d_ff or 4 * self.d_model

    @property
    def n_params(self) -> int:
        """Parameter count (for MFU math)."""
        d, f, l, v = self.d_model, self.ff, self.n_layer, self.vocab_size
        per_block = 4 * d * d + (2 * d * f + d + f) + 4 * d  # attn + mlp + lns
        if self.moe:
            per_block += self.moe.n_experts * 2 * d * f - (2 * d * f + d + f)
        return v * d + self.max_seq * d + l * per_block + 2 * d


# Presets (public GPT-2 sizes).
def gpt2_small():
    return GPT2Config(n_layer=12, n_head=12, d_model=768)


def gpt2_medium():
    return GPT2Config(n_layer=24, n_head=16, d_model=1024)


def gpt2_large():
    return GPT2Config(n_layer=36, n_head=20, d_model=1280)


def gpt2_xl():
    """The 1.5B north-star config."""
    return GPT2Config(n_layer=48, n_head=25, d_model=1600)


def gpt2_tiny():
    """Test-sized config."""
    return GPT2Config(
        vocab_size=256, max_seq=128, n_layer=2, n_head=4, d_model=64, remat=False
    )


# ------------------------------------------------------------------ params
def _init_block(key, cfg: GPT2Config):
    k1, k2 = jax.random.split(key)
    block = {
        "ln1": {
            "scale": jnp.ones((cfg.d_model,), cfg.param_dtype),
            "bias": jnp.zeros((cfg.d_model,), cfg.param_dtype),
        },
        "attn": L.init_attention(k1, cfg.d_model, cfg.n_head, cfg.param_dtype),
        "ln2": {
            "scale": jnp.ones((cfg.d_model,), cfg.param_dtype),
            "bias": jnp.zeros((cfg.d_model,), cfg.param_dtype),
        },
    }
    if cfg.moe:
        block["moe"] = L.init_moe(k2, cfg.d_model, cfg.ff, cfg.moe, cfg.param_dtype)
    else:
        block["mlp"] = L.init_mlp(k2, cfg.d_model, cfg.ff, cfg.param_dtype)
    return block


def init(key, cfg: GPT2Config):
    ke, kp, kb = jax.random.split(key, 3)
    blocks = jax.vmap(lambda k: _init_block(k, cfg))(jax.random.split(kb, cfg.n_layer))
    return {
        "wte": (jax.random.normal(ke, (cfg.vocab_size, cfg.d_model)) * 0.02).astype(
            cfg.param_dtype
        ),
        "wpe": (jax.random.normal(kp, (cfg.max_seq, cfg.d_model)) * 0.01).astype(
            cfg.param_dtype
        ),
        "blocks": blocks,
        "ln_f": {
            "scale": jnp.ones((cfg.d_model,), cfg.param_dtype),
            "bias": jnp.zeros((cfg.d_model,), cfg.param_dtype),
        },
    }


def logical_axes(cfg: GPT2Config):
    """Pytree of logical-axis names matching init()'s structure. Stacked
    block leaves get a leading 'layers' axis (mapped to pp only by the
    pipelined path, which re-chunks explicitly)."""
    ln = {"scale": ("embed",), "bias": ("embed",)}
    block = {
        "ln1": ln,
        "attn": dict(L.ATTENTION_LOGICAL),
        "ln2": ln,
    }
    if cfg.moe:
        block["moe"] = dict(L.MOE_LOGICAL)
    else:
        block["mlp"] = dict(L.MLP_LOGICAL)
    block = jax.tree_util.tree_map(
        lambda names: ("layers",) + tuple(names),
        block,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    return {
        "wte": ("vocab", "embed"),
        "wpe": (None, "embed"),
        "blocks": block,
        "ln_f": ln,
    }


def partition_specs(cfg: GPT2Config, rules=None):
    return jax.tree_util.tree_map(
        lambda names: sh.spec(*names, rules=rules),
        logical_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


# ----------------------------------------------------------------- forward
def _resolve_attention(cfg: GPT2Config, mesh: Optional[Mesh]) -> str:
    if cfg.attention != "auto":
        return cfg.attention
    if mesh is not None and dict(mesh.shape).get("sp", 1) > 1:
        return "ring"
    if jax.default_backend() == "tpu":
        return "flash"
    return "reference"


def _block_apply(block, x, cfg: GPT2Config, impl: str):
    cd = cfg.dtype
    h = L.layer_norm(x, block["ln1"]["scale"], block["ln1"]["bias"])
    x = x + L.apply_attention(block["attn"], h, causal=True, impl=impl, compute_dtype=cd)
    h = L.layer_norm(x, block["ln2"]["scale"], block["ln2"]["bias"])
    if cfg.moe:
        m, aux = L.apply_moe(block["moe"], h, cfg.moe, compute_dtype=cd)
    else:
        m, aux = L.apply_mlp(block["mlp"], h, compute_dtype=cd), jnp.float32(0)
    return x + m, aux


def embed(params, tokens, cfg: GPT2Config):
    S = tokens.shape[1]
    x = jnp.take(params["wte"], tokens, axis=0) + params["wpe"][:S]
    return x.astype(cfg.dtype)


def unembed(params, x, cfg: GPT2Config):
    """Vocab projection in bf16 with f32 MXU accumulation. The earlier f32
    einsum + log_softmax loss tail cost ~100ms/step at batch 16 on v5e (vs
    34ms this way, measured) — the f32 [B,S,V] matmul runs far off MXU peak
    and log_softmax materializes a second 3.3 GB tensor."""
    x = L.layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"])
    return jax.lax.dot_general(
        x.astype(cfg.dtype), params["wte"].astype(cfg.dtype),
        (((2,), (1,)), ((), ())), preferred_element_type=jnp.float32,
    )


def forward(params, tokens, cfg: GPT2Config, mesh: Optional[Mesh] = None):
    """tokens [B, S] -> (logits [B, S, V] f32, moe aux loss scalar)."""
    impl = _resolve_attention(cfg, mesh)
    x = embed(params, tokens, cfg)
    if mesh is not None:
        x = sh.constrain(x, mesh, "batch", "seq", "embed")

    def body(carry, block):
        x, aux = carry
        x, a = _block_apply(block, x, cfg, impl)
        if mesh is not None:
            x = sh.constrain(x, mesh, "batch", "seq", "embed")
        return (x, aux + a), None

    if cfg.remat:
        body = jax.checkpoint(body)
    (x, aux), _ = jax.lax.scan(body, (x, jnp.float32(0)), params["blocks"])
    logits = unembed(params, x, cfg)
    if mesh is not None:
        logits = sh.constrain(logits, mesh, "batch", "seq", "vocab")
    return logits, aux / cfg.n_layer


def forward_pipelined(
    params,
    tokens,
    cfg: GPT2Config,
    mesh: Mesh,
    *,
    n_microbatches: int = 4,
):
    """Pipeline-parallel forward: block stack split into pp stages,
    embedding/unembedding outside the pipeline (they are cheap and
    tp/dp-sharded). Attention inside stages is flash/reference (see
    pipeline.py for the sp+pp limitation)."""
    n_pp = dict(mesh.shape).get("pp", 1)
    if cfg.n_layer % n_pp:
        raise ValueError(f"n_layer={cfg.n_layer} not divisible by pp={n_pp}")
    if cfg.moe is not None:
        # The GPipe carry is activations-only; the MoE aux loss would be
        # silently dropped (router collapse with no signal). Refuse loudly
        # until aux is threaded through the pipeline carry.
        raise NotImplementedError(
            "pipelined forward does not yet propagate the MoE aux loss; "
            "use pp=1 with MoE or a dense (non-MoE) config with pp>1"
        )
    n_sp = dict(mesh.shape).get("sp", 1)
    # pp×sp composition: ONE flat manual region over {pp, sp} with the
    # per-shard ring attention inside stages (a nested sp-shard_map in the
    # pp scan does not differentiate — DuplicateSpecError in transpose).
    if n_sp > 1:
        impl = "ring_local"
        manual_axes = ("sp",)
        from jax.sharding import PartitionSpec as _P

        mb_spec = _P(None, None, "sp", None)   # [M, B_mb, S, D]
    else:
        impl = "flash" if jax.default_backend() == "tpu" else "reference"
        manual_axes = ()
        mb_spec = None
    per_stage = cfg.n_layer // n_pp
    staged = jax.tree_util.tree_map(
        lambda leaf: leaf.reshape((n_pp, per_stage) + leaf.shape[1:]),
        params["blocks"],
    )

    def stage_fn(stage_blocks, x):
        def body(x, block):
            y, _ = _block_apply(block, x, cfg, impl)
            return y, None

        if cfg.remat:
            body = jax.checkpoint(body)
        x, _ = jax.lax.scan(body, x, stage_blocks)
        return x

    x = embed(params, tokens, cfg)
    x = sh.constrain(x, mesh, "batch", "seq", "embed")
    mb = microbatch(x, n_microbatches)
    y = gpipe(stage_fn, staged, mb, mesh, manual_axes=manual_axes,
              mb_spec=mb_spec)
    x = unmicrobatch(y)
    logits = unembed(params, x, cfg)
    return sh.constrain(logits, mesh, "batch", "seq", "vocab"), jnp.float32(0)


def loss_fn(
    params,
    batch,
    cfg: GPT2Config,
    mesh: Optional[Mesh] = None,
    *,
    pipelined: bool = False,
    n_microbatches: int = 4,
) -> Tuple[jnp.ndarray, dict]:
    """batch: {"tokens" [B,S+1] int32}. Next-token cross-entropy."""
    tokens = batch["tokens"][:, :-1]
    targets = batch["tokens"][:, 1:]
    if pipelined:
        logits, aux = forward_pipelined(
            params, tokens, cfg, mesh, n_microbatches=n_microbatches
        )
    else:
        logits, aux = forward(params, tokens, cfg, mesh)
    # -log p(target) = logsumexp(logits) - logits[target]; computed without
    # materializing log_softmax's full [B,S,V] output (HBM-bandwidth win).
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tl = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    loss = jnp.mean(lse - tl)
    total = loss + cfg.aux_loss_weight * aux
    return total, {"loss": loss, "aux_loss": aux, "total_loss": total}
