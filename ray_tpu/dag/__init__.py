"""Lazy task/actor DAG authoring (reference: python/ray/dag/dag_node.py —
DAGNode, FunctionNode, ClassNode, ClassMethodNode, InputNode).

`fn.bind(...)` builds nodes without executing; `node.execute(*inputs)`
materializes the graph into tasks/actor calls and returns ObjectRefs. This is
the substrate for Serve deployment graphs and Workflow DAGs.
"""
from __future__ import annotations

from typing import Any


class DAGNode:
    """A node in a lazily-built computation graph."""

    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    # -- traversal ------------------------------------------------------------

    def _map_children(self, fn):
        args = tuple(fn(a) if isinstance(a, DAGNode) else a
                     for a in self._bound_args)
        kwargs = {k: fn(v) if isinstance(v, DAGNode) else v
                  for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def children(self) -> list["DAGNode"]:
        out = [a for a in self._bound_args if isinstance(a, DAGNode)]
        out += [v for v in self._bound_kwargs.values()
                if isinstance(v, DAGNode)]
        return out

    def execute(self, *input_values, _cache: dict | None = None):
        """Materialize the DAG rooted here. Shared sub-nodes execute once."""
        cache: dict[int, Any] = {} if _cache is None else _cache
        return self._execute_impl(input_values, cache)

    def _execute_impl(self, input_values, cache):
        key = id(self)
        if key in cache:
            return cache[key]
        args, kwargs = self._map_children(
            lambda child: child._execute_impl(input_values, cache))
        result = self._execute_self(args, kwargs, input_values)
        cache[key] = result
        return result

    def _execute_self(self, args, kwargs, input_values):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for the value supplied at execute() time. Supports
    `with InputNode() as x:` authoring like the reference."""

    def __init__(self, index: int = 0):
        super().__init__((), {})
        self._index = index

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def _execute_self(self, args, kwargs, input_values):
        if self._index >= len(input_values):
            raise ValueError(
                f"DAG executed with {len(input_values)} inputs but an "
                f"InputNode expects index {self._index}")
        return input_values[self._index]


class FunctionNode(DAGNode):
    """fn.bind(...) — a task invocation."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_self(self, args, kwargs, input_values):
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """ActorClass.bind(...) — an actor instantiation."""

    def __init__(self, actor_cls, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls

    def _execute_self(self, args, kwargs, input_values):
        return self._actor_cls.remote(*args, **kwargs)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _UnboundMethodNode(self, name)


class _UnboundMethodNode:
    def __init__(self, class_node: ClassNode, method_name: str):
        self._class_node = class_node
        self._method_name = method_name

    def bind(self, *args, **kwargs):
        return ClassMethodNode(
            (self._class_node, self._method_name), args, kwargs)


class ClassMethodNode(DAGNode):
    """actor_method.bind(...) or class_node.method.bind(...)."""

    def __init__(self, target, args: tuple, kwargs: dict):
        super().__init__(args, kwargs)
        self._target = target

    def children(self):
        out = super().children()
        if isinstance(self._target, tuple):
            out.append(self._target[0])
        return out

    def _execute_impl(self, input_values, cache):
        key = id(self)
        if key in cache:
            return cache[key]
        args, kwargs = self._map_children(
            lambda child: child._execute_impl(input_values, cache))
        if isinstance(self._target, tuple):
            class_node, method_name = self._target
            handle = class_node._execute_impl(input_values, cache)
            result = getattr(handle, method_name).remote(*args, **kwargs)
        else:
            result = self._target.remote(*args, **kwargs)
        cache[key] = result
        return result


__all__ = ["DAGNode", "InputNode", "FunctionNode", "ClassNode",
           "ClassMethodNode"]
