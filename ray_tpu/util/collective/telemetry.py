"""Data-plane telemetry for collective ops.

PR 2 made the control plane observable; this module does the same for
the part the paper cares about — the collective layer. Three planes,
all behind the single ``RAY_TPU_INTERNAL_TELEMETRY=0`` kill switch:

- metrics: every op records ``ray_tpu_collective_latency_seconds`` and
  ``ray_tpu_collective_bytes_total`` tagged (op, backend, group) into
  the internal CATALOG (_private/telemetry.py), so ``metrics_summary()``
  / the dashboard's /metrics see per-op latency histograms and payload
  throughput with no extra wiring;
- spans: each op emits a span into BOTH the chrome-trace timeline
  (_private/profiling.py, µs ``ts``/``dur``) and util/tracing
  (``*TimeUnixNano``) — the tracing span inherits the executing task's
  context, so a collective issued inside a remote task shows up as a
  child of that task's trace (satellite: both clocks, no unit bugs);
- rank timings: each rank's (group, seq, op, start, end) record is
  buffered locally and flushed by a background thread to the group's
  rendezvous actor — the one process that sees every rank — where
  ``GroupTimingAggregator`` runs the straggler detector per completed
  (group, seq) and emits a ``COLLECTIVE_STRAGGLER`` cluster event
  naming the late ranks (2011.03641's observation: per-step stragglers
  dominate scaling behavior; the ICI-aware scheduler needs this signal).

Hot-path budget: with telemetry disabled an op pays one attribute read.
Enabled, it pays two span appends, one histogram observe, one counter
inc, and one lock'd list append (~10µs) — the flush RPC never runs on
the op path (see the <5% overhead guard in
tests/test_zz_collective_telemetry.py).

Clock caveat: rank timings use ``time.time()`` on each member host, so
cross-host straggler lags include NTP-level clock skew (ms-scale) —
fine for the >= tens-of-ms lags the detector's floor targets, not for
µs-scale ICI asymmetry.
"""
from __future__ import annotations

import collections
import statistics
import threading
import time

from ray_tpu._private import events as _events
from ray_tpu._private import profiling as _prof
from ray_tpu._private import telemetry as _tm

# flush the local timing buffer early once it holds this many records
# (the timer normally fires first; this bounds memory under op storms)
_FLUSH_HIGH_WATER = 64
_MAX_PENDING_SEQS = 256      # aggregator: completed-seq working set bound


def payload_nbytes(tensor) -> int:
    """Payload size of one rank's input/output (numpy and jax arrays
    both expose .nbytes) — accounted bytes are payload, not wire bytes
    (a ring allreduce moves ~2x payload per rank; keeping the metric
    algorithm-independent makes it comparable across backends)."""
    n = getattr(tensor, "nbytes", None)
    if n is not None:
        try:
            return int(n)
        except (TypeError, ValueError):
            return 0
    return 0


def run_op(g, op: str, seq, body, payload=None,
           measure_result: bool = False):
    """Execute one collective op body under full data-plane telemetry.

    `g` is the _GroupState; `seq` is the group op sequence (None for
    p2p ops, which have per-channel numbering and no full-group timing
    record). Byte accounting comes from `payload` (the op's input
    array) or, with `measure_result=True`, from the return value
    (recv: the payload is only known afterwards) — sized HERE, after
    the kill-switch check, so a disabled op pays only the bool."""
    if not _tm.ENABLED:
        return body()
    from ray_tpu.parallel import step_anatomy as _sa
    from ray_tpu.util import tracing

    nbytes = payload_nbytes(payload) if payload is not None else 0
    tags = {"op": op, "backend": g.backend, "group": g.name}
    # the active train step (if any): stamped into both span planes and
    # the rank-timing record, and an activity interval goes to the
    # step-anatomy ring so per-step comm attribution fuses by step_id
    # instead of wall-clock windows. One tuple read when inactive.
    step = _sa.current()
    step_id = step[0] if step is not None else None
    start = time.time()
    t0 = time.perf_counter()
    mono0 = time.monotonic()
    with _prof.record_span("collective", f"collective::{op}",
                           {"group": g.name, "backend": g.backend,
                            "seq": seq, "bytes": nbytes,
                            "step": step_id}):
        with tracing.span(f"collective {op}", "INTERNAL",
                          attributes={"group": g.name,
                                      "backend": g.backend, "seq": seq,
                                      "step": step_id}):
            result = body()
    dur = time.perf_counter() - t0
    if step is not None:
        # blocking iff the op ran on the thread driving the step loop
        # (today's synchronous collectives always do; a future async
        # bucketed-DDP flusher records background comm here)
        _sa.record_activity(
            "collective", mono0, mono0 + dur,
            blocking=threading.get_ident() == _sa._cur_thread,
            op=op, group=g.name)
    if measure_result:
        nbytes = payload_nbytes(result)
    _tm.observe("ray_tpu_collective_latency_seconds", dur, tags=tags)
    if nbytes:
        _tm.counter_inc("ray_tpu_collective_bytes_total", float(nbytes),
                        tags=tags)
    if seq is not None and g.world_size > 1:
        _reporter.add({"group": g.name, "op": op, "seq": int(seq),
                       "rank": g.rank, "world_size": g.world_size,
                       "start": start, "end": start + dur,
                       "bytes": nbytes, "step": step_id})
    return result


# --------------------------------------------------------------- reporting


class _TimingReporter:
    """Per-process buffer of rank-timing records, flushed OFF the op
    path by a daemon thread to each group's rendezvous actor (the
    flush is a fire-and-forget actor call; a dead/destroyed group just
    drops its batch)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._buf: list[dict] = []
        self._wake = threading.Event()
        self._thread: threading.Thread | None = None

    def add(self, record: dict):
        with self._lock:
            self._buf.append(record)
            n = len(self._buf)
            # (re)start on demand: the loop quiesces itself once the
            # buffer is drained and every group is gone
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="collective-timing-flush")
                self._thread.start()
        if n >= _FLUSH_HIGH_WATER:
            self._wake.set()

    def _loop(self):
        from ray_tpu._private.config import get_config
        from ray_tpu.util.collective import collective as _col

        while True:
            self._wake.wait(
                timeout=float(get_config("collective_timing_flush_s")))
            self._wake.clear()
            self.flush()
            # quiesce instead of waking 4x/s forever in a process whose
            # collective life is over; add() restarts the thread
            with self._lock:
                done = not self._buf and not _col._manager._groups
                if done:
                    self._thread = None
            if done:
                return

    def flush(self) -> int:
        """Ship buffered records to their groups' rendezvous actors.
        Synchronously callable (tests; group teardown). Returns the
        number of records handed off or dropped."""
        with self._lock:
            buf, self._buf = self._buf, []
        if not buf:
            return 0
        by_group: dict[str, list] = {}
        for r in buf:
            by_group.setdefault(r["group"], []).append(r)
        from ray_tpu.util.collective import collective as _col

        for gname, recs in by_group.items():
            state = _col._manager._groups.get(gname)
            store = getattr(state, "store", None)
            if store is None:
                continue   # group destroyed / never had a rendezvous
            try:
                store.report_timings.remote(recs)
            except Exception:
                pass       # telemetry must never fail the data plane
        return len(buf)


_reporter = _TimingReporter()


def flush_timings():
    """Force-flush this process's buffered rank timings (tests)."""
    _reporter.flush()


# --------------------------------------------------------------- detection


def detect_stragglers(timings: list[dict], multiple: float | None = None,
                      min_lag_s: float | None = None):
    """Flag ranks whose arrival lag exceeds a configurable multiple of
    the group median.

    `timings`: one record per rank with at least {"rank", "start"}.
    A rank's lag is its op start time minus the earliest rank's start;
    rank r is flagged when ``lag_r > max(multiple * median(lags of the
    OTHER ranks), min_lag_s)`` (strictly greater). The leave-one-out
    median matters: an extreme straggler must not raise the bar it is
    judged against — with a plain group median a 2-rank group could
    never flag anything (the laggard's own lag IS half the median), and
    one huge lag in a small group masks itself. The floor keeps a tight
    group (median ~ 0) from flagging µs-scale jitter. Returns
    (stragglers, lags, median_lag) where stragglers is a list of
    (rank, lag_s) sorted by lag desc and median_lag is the full-group
    median (reported in the event for context).
    """
    from ray_tpu._private.config import get_config

    if multiple is None:
        multiple = float(get_config("collective_straggler_multiple"))
    if min_lag_s is None:
        min_lag_s = float(get_config("collective_straggler_min_lag_s"))
    starts = {int(r["rank"]): float(r["start"]) for r in timings}
    if len(starts) < 2:
        return [], {}, 0.0
    t0 = min(starts.values())
    lags = {rank: s - t0 for rank, s in starts.items()}
    median = statistics.median(lags.values())
    # leave-one-out medians from one sort: removing sorted index i
    # leaves m = n-1 values whose median is index math, not a re-sort
    pairs = sorted(lags.items(), key=lambda kv: kv[1])
    vals = [lag for _, lag in pairs]
    n = len(vals)
    m = n - 1

    def _median_excluding(i: int) -> float:
        def at(j: int) -> float:            # j-th of the remaining m
            return vals[j] if j < i else vals[j + 1]
        if m % 2:
            return at(m // 2)
        return 0.5 * (at(m // 2 - 1) + at(m // 2))

    stragglers = []
    for i, (rank, lag) in enumerate(pairs):
        if lag > max(multiple * _median_excluding(i), min_lag_s):
            stragglers.append((rank, lag))
    stragglers.sort(key=lambda p: -p[1])
    return stragglers, lags, median


class GroupTimingAggregator:
    """Lives inside a group's rendezvous actor: accumulates per-(seq)
    rank-timing records and, once every rank has reported a seq, runs
    the straggler detector and emits a COLLECTIVE_STRAGGLER cluster
    event (the actor's own event ring rides the normal events_snapshot
    fan-out into list_cluster_events). Bounded: at most
    ``_MAX_PENDING_SEQS`` incomplete seqs are kept (drop-oldest — a
    rank that never reports must not grow the table forever)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._pending: dict[int, dict[int, dict]] = {}
        self._order: collections.deque = collections.deque()
        # completed seqs (bounded): a duplicated/retried report for an
        # already-evaluated seq must be a no-op, not resurrect a slot
        # that can never complete again
        self._done: collections.deque = collections.deque()
        self._done_set: set = set()
        self._lock = threading.Lock()
        self.stragglers_found = 0

    def ingest(self, records: list[dict]):
        complete = []
        with self._lock:
            for r in records:
                seq = int(r["seq"])
                if seq in self._done_set:
                    continue
                slot = self._pending.get(seq)
                if slot is None:
                    slot = self._pending[seq] = {}
                    self._order.append(seq)
                    while len(self._order) > _MAX_PENDING_SEQS:
                        self._pending.pop(self._order.popleft(), None)
                slot[int(r["rank"])] = r
                if len(slot) == self.world_size:
                    self._pending.pop(seq, None)
                    if len(self._done) >= _MAX_PENDING_SEQS:
                        self._done_set.discard(self._done.popleft())
                    self._done.append(seq)
                    self._done_set.add(seq)
                    complete.append((seq, slot))
        for seq, slot in complete:
            self._evaluate(seq, slot)

    def _evaluate(self, seq: int, slot: dict[int, dict]):
        recs = list(slot.values())
        stragglers, lags, median = detect_stragglers(recs)
        if not stragglers:
            return
        self.stragglers_found += len(stragglers)
        group = recs[0].get("group")
        op = recs[0].get("op")
        # op_seq, not seq: the event ring reserves `seq` for its own
        # per-process dedup counter
        _events.record("COLLECTIVE_STRAGGLER", group=group, op=op,
                       op_seq=seq, ranks=[rank for rank, _ in stragglers],
                       lags_s={str(rank): round(lag, 6)
                               for rank, lag in stragglers},
                       median_lag_s=round(median, 6),
                       world_size=self.world_size)
        _tm.counter_inc("ray_tpu_collective_stragglers_total",
                        float(len(stragglers)),
                        tags={"group": str(group), "op": str(op)})
