"""Async op handles for the collective plane.

The ROADMAP's overlap arc ("Exploring the limits of Concurrency in ML
Training on Google TPUs", arXiv:2011.03641) needs a primitive the
synchronous collective API cannot express: *start* a collective now,
*finish* it later, and do useful work in between. This module is that
primitive, shaped like Ray's own async object-ref model
(arXiv:1712.05889): an op submission returns a ``CollectiveHandle``
future with ``wait(timeout)`` / ``poll()`` / ``result()``.

Execution model — one **issue thread per group** (``IssueQueue``):

- Submissions enqueue (FIFO) with their group op-seq already assigned
  on the caller's thread, so the per-group sequence order every rank
  must agree on (the standard collective contract) is fixed at submit
  time, not at execution time.
- The issue thread executes ops strictly in submission order, one at a
  time — at most one op per group is ever on the wire from this rank,
  exactly like the synchronous API, so the mailbox seq validation and
  the receive-buffer pool see the same traffic shape they always did.
- Synchronous ops on a group with async ops in flight first ``drain()``
  the queue (the module API in ``collective.py`` does this), keeping
  mixed sync/async call sites ordered without any new contract.

Because the op body runs on the issue thread — NOT the thread driving
the train loop — the step-anatomy plane records its comm interval as
*background* for free (``telemetry.run_op`` stamps ``blocking`` iff the
op ran on the loop's own thread; the hook PR 11 left ready). A caller
that blocks in ``wait()`` while a step is active records that wait as
an *exposed* comm interval, so hidden/exposed attribution stays honest:
comm is hidden only where nobody was blocked on it.

Failure semantics compose with the gang-FT plane (PR 5): a poisoned
group fails the IN-FLIGHT op fast (its ``col_take`` raises
``CollectiveGroupError`` the moment the poison lands), and the issue
loop then fails every still-QUEUED handle with the same error
immediately — pending handles surface the gang failure within the
poison-latency bound instead of serially burning op timeouts. Group
destroy (``close``) fails queued handles the same way.

Lock discipline (RTL107 covers this module): handle completion state
flips ONLY under the issue queue's condition, waiters park in
``wait_for`` under it, and the op body itself always runs with the
condition released.
"""
from __future__ import annotations

import collections
import threading

from ray_tpu._private import telemetry as _tm


def _default_timeout() -> float:
    from ray_tpu._private.config import get_config

    return float(get_config("collective_op_timeout_s"))


class CollectiveHandle:
    """Future for one asynchronously issued collective op.

    Completion state is guarded by the owning group's issue condition
    (shared with the queue — one lock protects the whole issue-thread
    state). ``poll()`` is a single flag read; ``wait``/``result`` park
    on the condition until the issue thread finishes the op.
    """

    __slots__ = ("group", "op", "seq", "_cond", "_done", "_result",
                 "_error", "done_at")

    def __init__(self, group: str, op: str, seq, cond):
        self.group = group
        self.op = op
        self.seq = seq
        self._cond = cond
        self._done = False
        self._result = None
        self._error = None
        # time.perf_counter() stamp of COMPLETION (set by _finish):
        # latency consumers must measure launch→done_at, not
        # launch→harvest — a caller that parks on other work before
        # result() would otherwise inflate the op's apparent duration
        self.done_at: float | None = None

    def poll(self) -> bool:
        """True once the op finished (successfully or not). Never
        blocks — one attribute read, safe on hot paths."""
        return self._done

    def wait(self, timeout: float | None = None):
        """Block until the op completes; raise its error if it failed
        (e.g. ``CollectiveGroupError`` when the gang was poisoned while
        this op was pending) or ``TimeoutError`` after ``timeout``
        seconds (default: the collective op timeout). While a
        step-anatomy step is active, a wait that actually blocked is
        recorded as an EXPOSED comm interval — the part of background
        comm the caller could not hide."""
        if not self._done:
            if timeout is None:
                timeout = _default_timeout()
            stamp = _tm.ENABLED
            if stamp:
                import time as _time

                from ray_tpu.parallel import step_anatomy as _sa

                t0 = _time.monotonic()
            with self._cond:
                ok = self._cond.wait_for(lambda: self._done,
                                         timeout=timeout)
            if stamp:
                t1 = _time.monotonic()
                if t1 > t0:
                    # blocking iff THIS is the thread driving the step
                    # loop — the same rule run_op applies. A helper
                    # thread harvesting handles while the loop computes
                    # must not inflate comm_exposed (the loop was never
                    # blocked); its wait stays background.
                    _sa.record_activity(
                        "collective", t0, t1,
                        blocking=threading.get_ident() == _sa._cur_thread,
                        op=f"{self.op}_wait", group=self.group)
            if not ok:
                raise TimeoutError(
                    f"collective {self.op} (group {self.group!r}, seq "
                    f"{self.seq}) did not complete within {timeout}s")
        if self._error is not None:
            raise self._error
        return True

    def result(self, timeout: float | None = None):
        """``wait()`` and return the op's value."""
        self.wait(timeout)
        return self._result

    # -- issue-thread side -------------------------------------------------

    def _finish(self, result=None, error=None):
        """Complete the handle (issue thread / queue teardown only).
        Must be called with the condition RELEASED — it takes it."""
        import time as _time

        with self._cond:
            self._result = result
            self._error = error
            self.done_at = _time.perf_counter()
            self._done = True
            self._cond.notify_all()


class IssueQueue:
    """Per-group background issue thread: executes submitted collective
    op thunks strictly in submission order. The thread is started
    lazily on the first submission (sync-only groups never pay for it)
    and exits when the queue is closed."""

    def __init__(self, group: str):
        self.group = group
        self._cond = threading.Condition()
        self._queue: collections.deque = collections.deque()
        self._inflight = 0          # queued + executing (gauge source)
        self._thread: threading.Thread | None = None
        self._closed = False

    # -- caller side -------------------------------------------------------

    def submit(self, op: str, seq, thunk) -> CollectiveHandle:
        handle = CollectiveHandle(self.group, op, seq, self._cond)
        with self._cond:
            if self._closed:
                raise RuntimeError(
                    f"collective group {self.group!r} was destroyed; "
                    f"async submission refused")
            self._queue.append((handle, thunk))
            self._inflight += 1
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name=f"col-issue-{self.group}")
                self._thread.start()
            self._cond.notify_all()
        self._note_inflight()
        return handle

    def drain(self, timeout: float | None = None):
        """Block until every submitted op has completed — the ordering
        barrier synchronous ops take before touching a group with async
        work in flight. Errors stay on their handles (the sync op that
        follows hits the same group state and raises on its own).

        ``timeout`` bounds PROGRESS, not the whole drain: every queued
        op is individually bounded by the op timeout, so a deep healthy
        window must not spuriously fail here — drain only raises when
        no op completes within one timeout window."""
        if self._inflight == 0:
            return
        if timeout is None:
            timeout = _default_timeout()
        with self._cond:
            while self._inflight > 0:
                before = self._inflight
                ok = self._cond.wait_for(
                    lambda: self._inflight == 0
                    or self._inflight < before,
                    timeout=timeout)
                if not ok:
                    raise TimeoutError(
                        f"collective group {self.group!r}: async issue "
                        f"queue made no progress in {timeout}s "
                        f"({self._inflight} ops pending)")

    def pending(self) -> int:
        return self._inflight

    def close(self, reason: str = "collective group destroyed"):
        """Fail every queued handle and stop the issue thread. The op
        currently executing (if any) finishes on its own — its handle
        completes or errors through the normal path."""
        from ray_tpu import exceptions as exc

        drained = []
        with self._cond:
            if self._closed:
                return
            self._closed = True
            while self._queue:
                drained.append(self._queue.popleft()[0])
            self._inflight -= len(drained)
            self._cond.notify_all()
        err = exc.CollectiveGroupError(self.group, (), reason)
        for h in drained:
            h._finish(error=err)
        self._note_inflight()

    # -- issue thread ------------------------------------------------------

    def _loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait()
                if not self._queue and self._closed:
                    return
                handle, thunk = self._queue.popleft()
            # run with the condition RELEASED: the op blocks on network
            # receives for up to the op timeout, and poll()/submit()
            # must stay responsive meanwhile
            result = error = None
            try:
                result = thunk()
            except BaseException as e:  # noqa: BLE001 — delivered via handle
                error = e
            handle._finish(result, error)
            # drop the locals BEFORE parking again: the thunk closure
            # pins the packed input array and `result` the reduced
            # output — without this an idle group's issue thread
            # retains the last bucket's buffers (MBs) indefinitely
            del handle, thunk, result
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()
            self._note_inflight()
            if error is not None:
                self._fail_pending_fast(error)
            del error

    def _fail_pending_fast(self, error: BaseException):
        """A poisoned group makes EVERY subsequent op on it fail; once
        one op raises CollectiveGroupError, fail the still-queued
        handles with the same error immediately instead of issuing each
        one to fail in turn — pending handles must surface a gang death
        within the poison-latency bound, not serialized behind it."""
        from ray_tpu import exceptions as exc

        if not isinstance(error, exc.CollectiveGroupError):
            return
        drained = []
        with self._cond:
            while self._queue:
                drained.append(self._queue.popleft()[0])
            self._inflight -= len(drained)
            if drained:
                self._cond.notify_all()
        for h in drained:
            h._finish(error=error)
        if drained:
            self._note_inflight()

    def _note_inflight(self):
        if _tm.ENABLED:
            _tm.gauge_set("ray_tpu_collective_async_inflight_tasks",
                          float(self._inflight),
                          tags={"group": self.group})
