"""XLA collective backend: a group IS a jax process world.

Group creation runs jax.distributed.initialize over the member processes
(coordinator = rank 0, address exchanged through the group's rendezvous
actor), materializing one global device world; every op then compiles to
the corresponding XLA collective (psum / all_gather / psum_scatter) via
shard_map over a Mesh spanning the group — on TPU these lower to ICI
collectives, on the CPU test world to the Gloo cross-process backend.

This is the retargeting SURVEY.md §5 prescribes for the reference's
NCCL/gloo groups (nccl_collective_group.py: communicator per group,
rendezvous via named actor): the "communicator" is the compiled program's
collective, the rendezvous carries only the coordinator address.

p2p send/recv are not SPMD ops (only two ranks participate) and ride the
host mailbox plane — same split as the reference, whose p2p also bypasses
collective rings (collective.py:531 send / :594 recv are point-to-point).
"""
from __future__ import annotations

import numpy as np

_OP_TO_LAX = ("sum", "product", "min", "max")


class XlaGroup:
    """Membership of this process in a jax.distributed world."""

    def __init__(self, name: str, world_size: int, rank: int,
                 coordinator: str):
        import jax

        self.name = name
        self.world_size = world_size
        self.rank = rank
        # One jax.distributed world per process (jax constraint); a second
        # xla group in the same process reuses it and must have the same
        # membership shape.
        already = jax.distributed.is_initialized() \
            if hasattr(jax.distributed, "is_initialized") else False
        if world_size > 1 and not already:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=world_size,
                process_id=rank,
            )
        if jax.process_count() not in (1, world_size):
            raise RuntimeError(
                f"xla group {name!r}: process already in a "
                f"{jax.process_count()}-process world, cannot host a "
                f"{world_size}-rank group")
        self._jax = jax
        self._mesh = None
        self._fns: dict = {}

    # -- mesh / compiled-op cache ------------------------------------------

    def _ensure_mesh(self):
        if self._mesh is None:
            jax = self._jax
            # one device per rank keeps the group axis == process axis
            devs = []
            by_proc: dict[int, list] = {}
            for d in jax.devices():
                by_proc.setdefault(d.process_index, []).append(d)
            for p in sorted(by_proc):
                devs.append(sorted(by_proc[p], key=lambda d: d.id)[0])
            self._mesh = jax.sharding.Mesh(np.array(devs), ("ranks",))
        return self._mesh

    def _global_array(self, arr, mesh=None, axis: str = "ranks",
                      world: int | None = None):
        """Stack this rank's array as its shard of a leading group axis.
        Works for numpy AND device-resident jax arrays (device_put moves
        device-to-device, no host staging); the pair-mesh p2p path reuses
        it with axis="pair", world=2."""
        jax = self._jax
        if mesh is None:
            mesh = self._ensure_mesh()
        if world is None:
            world = self.world_size
        spec = jax.sharding.PartitionSpec(axis, *([None] * arr.ndim))
        sharding = jax.sharding.NamedSharding(mesh, spec)
        local_dev = [d for d in mesh.devices.flat
                     if d.process_index == jax.process_index()][0]
        shard = jax.device_put(arr[None, ...], local_dev)
        return jax.make_array_from_single_device_arrays(
            (world,) + tuple(arr.shape), sharding, [shard]), sharding

    def _compiled(self, kind: str, op: str, shape, dtype):
        key = (kind, op, shape, dtype)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        mesh = self._ensure_mesh()
        ndim = len(shape)
        in_spec = P("ranks", *([None] * ndim))

        def reduce_term(x):
            # x: (1, *shape) block on this rank
            if op == "sum":
                return lax.psum(x, "ranks")
            if op == "max":
                return lax.pmax(x, "ranks")
            if op == "min":
                return lax.pmin(x, "ranks")
            # product via exp/log is lossy; use all_gather + prod
            g = lax.all_gather(x[0], "ranks")        # (world, *shape)
            return jax.numpy.prod(g, axis=0)[None]

        if kind == "allreduce":
            body = reduce_term
            out_spec = in_spec
        elif kind == "reducescatter":
            def body(x):
                r = reduce_term(x)[0]                # (*shape,)
                return lax.dynamic_slice_in_dim(
                    r, lax.axis_index("ranks") * (shape[0] //
                                                  self.world_size),
                    shape[0] // self.world_size, axis=0)[None]
            out_spec = in_spec
        elif kind == "allgather":
            def body(x):
                return lax.all_gather(x[0], "ranks")[None]
            out_spec = in_spec
        else:
            raise ValueError(kind)

        sm = jax.shard_map(body, mesh=mesh, in_specs=in_spec,
                           out_specs=out_spec)
        fn = jax.jit(sm)
        self._fns[key] = fn
        return fn

    def _compiled_broadcast(self, src: int, shape, dtype):
        """Binomial-tree broadcast over ppermute: ⌈log2(N)⌉ steps, total
        payload moved ≈ N-1 copies (a psum-of-zeros "broadcast" moves
        2(N-1)/N of an allreduce — this is the real thing)."""
        key = ("broadcast", src, shape, dtype)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        from jax import lax
        from jax.sharding import PartitionSpec as P

        mesh = self._ensure_mesh()
        N = self.world_size
        in_spec = P("ranks", *([None] * len(shape)))

        def body(x):
            # x holds the payload only on src; zero elsewhere
            idx = lax.axis_index("ranks")
            x = jax.numpy.where(idx == src, x, jax.numpy.zeros_like(x))
            have = 1            # effective ranks 0..have-1 hold the data
            while have < N:
                pairs = []
                for e in range(have):
                    te = e + have
                    if te < N:
                        pairs.append(((e + src) % N, (te + src) % N))
                recv = lax.ppermute(x, "ranks", perm=pairs)
                x = x + recv    # recv is zero except at the new holders
                have *= 2
            return x

        fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=in_spec,
                                   out_specs=in_spec))
        self._fns[key] = fn
        return fn

    # -- device-resident p2p ------------------------------------------------

    def _rank_device(self, rank: int):
        for d in self._jax.devices():
            if d.process_index == rank:
                return d
        raise RuntimeError(f"no device for rank {rank}")

    def _pair_fn(self, src: int, dst: int, shape, dtype):
        """Compiled 2-device ppermute over a SUB-mesh of the world: only
        the endpoints enter the program, so send/recv stays a
        point-to-point exchange (NCCL-send/recv analog) — on TPU the
        transfer rides ICI/DCN links, never the host mailbox plane."""
        key = ("p2p", src, dst, shape, dtype)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        jax = self._jax
        from jax import lax
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as P

        mesh = Mesh(
            np.array([self._rank_device(src), self._rank_device(dst)]),
            ("pair",))
        in_spec = P("pair", *([None] * len(shape)))

        def body(x):
            return lax.ppermute(x, "pair", perm=[(0, 1)])

        fn = jax.jit(jax.shard_map(body, mesh=mesh, in_specs=in_spec,
                                   out_specs=in_spec))
        self._fns[key] = (fn, mesh)
        return self._fns[key]

    def send_device(self, arr, dst: int):
        """Called on the SOURCE rank; pairs with recv_device(dst side).
        Device-resident inputs never stage through the host (device_put
        is device-to-device). Blocks until the transfer program ran
        (matched-call contract, same as NCCL send/recv)."""
        jax = self._jax
        if not self._is_device_array(arr):
            arr = np.asarray(arr)
        dtype = str(jax.numpy.dtype(arr.dtype))   # canonical (bfloat16!)
        fn, mesh = self._pair_fn(self.rank, dst, tuple(arr.shape), dtype)
        garr, _ = self._global_array(arr, mesh=mesh, axis="pair", world=2)
        jax.block_until_ready(fn(garr))

    def recv_device(self, shape, dtype, src: int):
        """Called on the DESTINATION rank; returns the payload as a
        device-resident jax array."""
        jax = self._jax
        dt = jax.numpy.dtype(dtype)   # resolves "bfloat16" via ml_dtypes
        fn, mesh = self._pair_fn(src, self.rank, tuple(shape), str(dt))
        zeros = np.zeros(tuple(shape), dt)
        garr, _ = self._global_array(zeros, mesh=mesh, axis="pair",
                                     world=2)
        out = fn(garr)
        return out.addressable_shards[0].data[0]

    # -- ops ----------------------------------------------------------------
    # Device residency: jax-array inputs stay on device end-to-end — the
    # result is returned as a jax array (no host round-trip); numpy inputs
    # round-trip through the host as before. One device per process carries
    # the group axis; an actor owning several chips spreads *data* over them
    # through the Train stack's global mesh, not through this per-rank API.

    def _is_device_array(self, arr) -> bool:
        return isinstance(arr, self._jax.Array)

    def _run(self, kind: str, arr, op: str = "sum"):
        keep_on_device = self._is_device_array(arr)
        if not keep_on_device:
            arr = np.asarray(arr)
        garr, _ = self._global_array(arr)
        fn = self._compiled(kind, op, tuple(arr.shape), str(arr.dtype))
        out = fn(garr)
        local = out.addressable_shards[0].data[0]
        if keep_on_device:
            return local
        return np.asarray(local)

    def allreduce(self, arr, op, seq):
        if self.world_size == 1:
            return arr if self._is_device_array(arr) else np.asarray(arr)
        return self._run("allreduce", arr, op)

    def reduce(self, arr, dst, op, seq):
        out = self.allreduce(arr, op, seq)
        return out if self.rank == dst else arr

    def broadcast(self, arr, src, seq):
        if self.world_size == 1:
            return arr if self._is_device_array(arr) else np.asarray(arr)
        keep = self._is_device_array(arr)
        if not keep:
            arr = np.asarray(arr)
        garr, _ = self._global_array(arr)
        fn = self._compiled_broadcast(src, tuple(arr.shape),
                                      str(arr.dtype))
        out = fn(garr)
        local = out.addressable_shards[0].data[0]
        return local if keep else np.asarray(local)

    def allgather(self, arr, seq) -> list:
        if self.world_size == 1:
            return [arr if self._is_device_array(arr) else np.asarray(arr)]
        stacked = self._run("allgather", arr)
        return [stacked[i] for i in range(self.world_size)]

    def reducescatter(self, arr, op, seq):
        if self.world_size == 1:
            return arr if self._is_device_array(arr) else np.asarray(arr)
        dim0 = arr.shape[0]
        if dim0 % self.world_size:
            # uneven leading dim: fall back to allreduce + local slice
            out = self._run("allreduce", arr, op)
            if self._is_device_array(out):
                splits = np.cumsum([len(s) for s in np.array_split(
                    np.empty(dim0), self.world_size)])[:-1]
                start = 0 if self.rank == 0 else int(splits[self.rank - 1])
                stop = int(splits[self.rank]) if self.rank < len(splits) \
                    else dim0
                return out[start:stop]
            return np.array_split(out, self.world_size, axis=0)[self.rank]
        return self._run("reducescatter", arr, op)

    def barrier(self, seq):
        self.allreduce(np.zeros((1,), np.float32), "sum", seq)

    def close(self):
        pass  # the jax.distributed world outlives individual groups
