"""Peer-to-peer host collectives: ring allreduce/allgather/reducescatter,
binomial-tree broadcast/reduce, dissemination barrier.

Replaces round 1's single-rendezvous-actor data path (every tensor funnelled
through one process, O(world x bytes) on one socket) with direct
worker-to-worker transfers, the same topology class the reference's
NCCL/gloo groups use (nccl_collective_group.py rings, pygloo rings). The
named group actor now rendezvouses MEMBERSHIP ONLY (rank -> worker addr);
data rides each member CoreWorker's mailbox (worker_runtime.rpc_col_push).

Data path (PR: pipelined zero-copy host collectives). Two modes:

- **pipelined** (default; kill switch ``RAY_TPU_COLLECTIVE_PIPELINE=0``):
  every hop is a one-way PUSH_OOB frame (``RpcClient.push_parts``) — no
  request/reply round trip; completion is detected by the receiver's own
  ``col_take`` with the op timeout as the failure detector, the shape
  NCCL/Gloo rings use. Ring payloads are split into
  ``collective_segment_bytes`` segments and double-buffered: the send of
  segment *k* for step *s+1* is posted the moment step *s*'s reduce of
  that segment finishes, so reduction overlaps transfer (cf. Horovod
  tensor fusion / DDP gradient bucketing). Tensors are framed via
  ``serialization.serialize_parts`` out-of-band buffers — the sender
  writes straight from the array memory, the receiver reduces in place
  from a pooled buffer (worker_runtime's per-(group, nbytes)
  receive-buffer pool), so steady-state allreduce does zero per-step
  allocations. When the membership spans several hosts with co-located
  ranks, allreduce reduces intra-host first and runs the inter-host ring
  over one leader per host (``collective_hierarchy``) — the DCN/ICI
  split the paper's topology-aware scheduler assumes.
- **legacy**: the original synchronous ``col_push`` request/reply ring,
  kept bit-for-bit as the kill-switch fallback and semantic reference.

Wire quantization (PR: block-quantized segmented collectives): with
``RAY_TPU_COLLECTIVE_WIRE_DTYPE=bf16|int8`` (default ``off`` — the
bit-exact path), eligible ring segments are quantized just before the
send (see ``wire.py`` for formats/eligibility/bounds) and
dequantize-accumulated on the receive, riding the same src/acc split —
quantization overlaps transfer exactly like the reduce does. The
allgather phase forwards the already-quantized frame unchanged, so each
payload is quantized ONCE per hop chain, and whichever rank computed a
chunk's final reduction decodes its own encoding back into ``acc``
before broadcasting it — every rank therefore returns byte-identical
results even though the wire is lossy. Eligibility is float32 ``sum``
on the pipelined path only; everything else (ints, float64,
prod/min/max, legacy mode) silently keeps the exact wire format, as do
individual segments the codec declines (non-finite int8 blocks,
sub-block tails). The intra-host hierarchy quantizes the INTER-host
leader ring only — same-host hops are shm/loopback, where the bytes
are nearly free and exactness is.

All algorithms key messages by (group, op-seq, phase, step[, segment]) so
concurrent ops and late arrivals never cross wires; collective calls must
be issued in the same order by every rank (standard collective contract,
as NCCL). Whenever the FLAT ring runs (hierarchy disabled or not
engaged — on a single host it never engages), both modes produce
bit-identical results: the pipelined path applies the same reduce
operands in the same order, just segment-wise. The intra-host-first
hierarchy necessarily changes the floating-point reduction NESTING
(locals fold at the leader before the inter-host ring), like any
hierarchical allreduce — exact to the flat ring for integer dtypes and
commutative-exact ops, within rounding for floats.
"""
from __future__ import annotations

import threading

import numpy as np

from ray_tpu import exceptions as exc
from ray_tpu._private import memory_anatomy as _ma
from ray_tpu._private import protocol as _protocol
from ray_tpu._private import serialization as ser
from ray_tpu._private import telemetry as _tm
from ray_tpu._private.protocol import (ConnectionLost, PyRpcClient,
                                       RpcClient)
from ray_tpu._private.worker_runtime import (ColShmRef, col_epoch_tag,
                                             col_oid_prefix, current_worker)
from ray_tpu.util.collective import wire as _wire
from ray_tpu.util.collective.async_handles import (CollectiveHandle,
                                                   IssueQueue)

_OPS = {
    "sum": np.add,
    "product": np.multiply,
    "min": np.minimum,
    "max": np.maximum,
}


def _split_bounds(total: int, parts: int) -> list[tuple[int, int]]:
    """np.array_split boundaries: the first (total % parts) chunks are
    one element longer. Every rank derives the same bounds locally."""
    base, extra = divmod(total, parts)
    bounds, lo = [], 0
    for i in range(parts):
        hi = lo + base + (1 if i < extra else 0)
        bounds.append((lo, hi))
        lo = hi
    return bounds


def _segments(lo: int, hi: int, step: int) -> list[tuple[int, int]]:
    out = []
    a = lo
    while a < hi:
        b = min(a + step, hi)
        out.append((a, b))
        a = b
    return out


def _materialize(val):
    """Copy frame-backed arrays out of their (pooled, about to be
    released) receive buffer before they escape to the caller. Values
    that own their memory pass through."""
    if isinstance(val, np.ndarray) and not val.flags["OWNDATA"]:
        return np.array(val)
    if isinstance(val, (list, tuple)):
        return type(val)(_materialize(v) for v in val)
    return val


class _ShmFrame(_protocol.OobFrame):
    """OobFrame over a pinned shm-store object (same-node segment
    transport): the view maps the store segment zero-copy. release()
    unpins, and by default also DELETES the object — pass delete=False
    when the same object id is being forwarded to the next ring hop
    (the last consumer in the chain deletes)."""

    __slots__ = ("_store", "oid", "_pin")

    def __init__(self, store, oid: bytes, pin):
        self._store = store
        self.oid = oid
        self._pin = pin
        self.view = pin.memoryview()

    @property
    def nbytes(self) -> int:
        return self.view.nbytes if self.view is not None else 0

    def release(self, delete: bool = True):
        pin, self._pin = self._pin, None
        if pin is None:
            return
        self.view = None
        try:
            pin.release()
        except Exception:
            pass
        if delete:
            try:
                self._store.delete_ephemeral(self.oid)
            except Exception:
                pass


class HostGroup:
    """This process's membership in one collective group."""

    def __init__(self, name: str, world_size: int, rank: int,
                 members: dict[int, tuple], epoch: int = 0,
                 rendezvous=None):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        # Incarnation epoch (minted by the rendezvous actor at group
        # creation): stamped into every col frame key and shm object id
        # so a REBUILT gang under the same name rejects stale traffic
        # from this one at ingest (worker_runtime.col_push_local).
        self.epoch = int(epoch)
        # rendezvous actor handle (None for bare unit-test groups): the
        # gang-wide poison fan-out rides it when this rank directly
        # observes a peer's death (connection loss)
        self._rendezvous = rendezvous
        self.members = {int(r): tuple(a) for r, a in members.items()}
        self._clients: dict[int, RpcClient] = {}
        self._client_mode: dict[int, bool] = {}    # rank -> built-for-
                                                   # pipelined?
        self._peer_nodes: dict[int, object] = {}   # rank -> node_id |
                                                   # (None, retry_at)
        self._oid_prefix = col_oid_prefix(name) + col_epoch_tag(self.epoch)
        self._seg_count = 0
        self._wire_codecs: dict[tuple, _wire.WireCodec] = {}
        self._wire_bytes: dict[str, int] = {}     # format -> ring bytes
        self._quant_samples: list[tuple] = []     # (format, err_ratio)
        self._worker = current_worker()
        if self._worker is None:
            raise RuntimeError("collective group requires a ray_tpu worker "
                               "or driver runtime in this process")
        # async op plane: per-group issue thread (lazy — the thread only
        # spawns on the first async submission; sync-only groups pay one
        # eagerly built Condition + deque)
        self._issue = IssueQueue(name)

    # -- plumbing -----------------------------------------------------------

    @staticmethod
    def _op_timeout() -> float:
        from ray_tpu._private.config import get_config

        return float(get_config("collective_op_timeout_s"))

    @staticmethod
    def _pipelined() -> bool:
        from ray_tpu._private.config import get_config

        return bool(get_config("collective_pipeline"))

    @staticmethod
    def _death_poisoning() -> bool:
        from ray_tpu._private.config import get_config

        return bool(get_config("collective_death_poisoning"))

    def _full_key(self, key: tuple, src: int) -> tuple:
        """(group, epoch, *op-key, src): every message is fenced by the
        incarnation epoch right after the group name."""
        return (self.name, self.epoch) + key + (src,)

    def _conn_dropped(self, rank: int, addr):
        """PyRpcClient on_close hook: the connection to `rank` died with
        no send in flight (in-flight failures raise ConnectionLost at
        the call site and go straight to _raise_peer_lost). Probe before
        poisoning: an idle drop whose peer still accepts connections
        (peer-side server hiccup, OS reaping an idle socket across a
        minutes-long compile/eval gap) must self-heal through _client's
        closed-client rebuild path — poisoning would convert a transport
        blip into a full gang checkpoint-restore restart and burn a
        FailureConfig.max_failures token. A genuinely dead peer refuses
        the probe within a connect round trip, keeping the fast path."""
        def _probe():
            import socket as _socket

            try:
                s = _socket.create_connection(tuple(addr)[:2], timeout=2.0)
                s.close()
                return   # peer alive: benign drop, client rebuilds lazily
            except OSError:
                pass
            self._peer_lost(rank)

        threading.Thread(target=_probe, daemon=True,
                         name="col-conn-probe").start()

    def _peer_lost(self, rank: int, cause: str = "connection lost"):
        """A peer's death was directly observed (its connection dropped
        or a send to it failed). Poison this process's view of the group
        so every pending/future take fails fast, fan the poison out
        gang-wide through the rendezvous actor (off-thread: this may run
        on a transport reader thread), and return the error to raise.
        Returns None when the death-poisoning kill switch is off —
        callers re-raise the original transport error, so
        RAY_TPU_COLLECTIVE_DEATH_POISONING=0 restores the legacy
        ConnectionLost/timeout contract exactly."""
        if not self._death_poisoning():
            return None
        reason = f"rank {rank} {cause}"
        err = exc.CollectiveGroupError(self.name, (rank,), reason)
        try:
            first = self._worker.col_poison_local(self.name, (rank,),
                                                  reason, epoch=self.epoch)
        except Exception:
            return err
        if first and self._rendezvous is not None:
            rdv, epoch = self._rendezvous, self.epoch

            def _notify():
                try:
                    rdv.poison.remote([rank], reason, epoch)
                except Exception:
                    pass

            threading.Thread(target=_notify, daemon=True,
                             name="col-poison-notify").start()
        return err

    def _raise_peer_lost(self, rank: int, e: BaseException, cause: str):
        """Raise for a transport failure talking to `rank`: the
        poison-path CollectiveGroupError, or — when the kill switch has
        death-poisoning off — the original transport error unchanged."""
        err = self._peer_lost(rank, cause)
        if err is None:
            raise e
        raise err from e

    def _segment_elems(self, itemsize: int) -> int:
        """Elements per ring segment: floor(collective_segment_bytes /
        itemsize), never below 1 element even when a single element
        exceeds the byte budget. Floor division means segments are
        always WHOLE-element — for itemsizes that don't divide the
        budget (non-power-of-two dtypes) a segment runs up to
        itemsize-1 bytes under it, and only the LAST segment of a chunk
        is ragged (``_segments``). Every rank derives the same element
        count locally; the int8 block-scale wire layout relies on
        exactly this (block boundaries are computed in elements, so
        sender and receiver always agree on where scales apply)."""
        from ray_tpu._private.config import get_config

        return max(1, int(get_config("collective_segment_bytes"))
                   // max(1, int(itemsize)))

    def _wire_ctx(self, dtype, op: str,
                  override=None) -> _wire.WireCodec | None:
        """The group's wire-quantization codec for one (dtype, op), or
        None for the exact path. ``off`` (the default) and the legacy
        ring always return None; an unknown format name raises rather
        than silently sending exact. Eligibility beyond the format
        knob: float32 ``sum`` only — ints and prod/min/max have no
        bounded-error story, float64 would LOSE precision through a
        float32-scaled wire. ``override`` is a per-CALL format name
        (sharded DDP opts buckets in individually); it replaces the
        config knob for this op but passes through the same
        normalization and eligibility checks."""
        from ray_tpu._private.config import get_config

        fmt = _wire.normalize_format(
            get_config("collective_wire_dtype") if override is None
            else override)
        if fmt is None:
            return None
        if not self._pipelined():
            return None   # legacy kill-switch ring stays bit-exact
        if np.dtype(dtype) != np.float32 or op != "sum":
            return None
        block = int(get_config("collective_quant_block"))
        key = (fmt, block)
        codec = self._wire_codecs.get(key)
        if codec is None:
            codec = self._wire_codecs[key] = _wire.WireCodec(fmt, block)
        return codec

    def _client(self, rank: int) -> RpcClient:
        # Pipelined mode deliberately uses the pure-Python client even
        # when the native core is available: push_parts writes segment
        # frames scatter-gather straight from the array memory (sendall
        # per part, zero assembly copy), where the native binding must
        # assemble one contiguous buffer per send. The wire format is
        # shared, so it talks to native AND Python servers alike; the
        # receive side stays on the peer's (native, off-GIL) server.
        # Legacy (kill-switch) mode keeps the default transport factory
        # so RAY_TPU_COLLECTIVE_PIPELINE=0 restores the round-4 data
        # path exactly, native client included.
        want_py = self._pipelined()
        c = self._clients.get(rank)
        addr = tuple(self.members[rank])
        # flavor staleness is judged against the mode the client was
        # BUILT under, not isinstance — the legacy factory legitimately
        # returns a PyRpcClient on pure-Python builds, and an
        # isinstance check would condemn it on every call
        if c is not None and (c.closed or tuple(c.addr) != addr
                              or self._client_mode.get(rank) != want_py):
            # stale: dead connection, the peer address changed under a
            # group reincarnation (a cached client to the OLD address
            # would win until it errored, landing frames on a ghost),
            # or the pipeline mode flipped transport flavor
            try:
                c.close()
            except Exception:
                pass
            c = None
            # a reincarnated peer may sit on a different node now —
            # its shm-eligibility verdict must be re-learned too
            self._peer_nodes.pop(rank, None)
        if c is None:
            try:
                if want_py:
                    # on_close fires only on connection LOSS (deliberate
                    # close() suppresses it): a dead peer poisons the
                    # group within TCP-reset + liveness-probe latency,
                    # not the op timeout — the NCCL-watchdog-beating
                    # fast path (the probe keeps an idle drop of a LIVE
                    # peer from gang-restarting the run)
                    c = PyRpcClient(
                        addr, timeout=self._op_timeout(),
                        on_close=(lambda r=rank, a=addr:
                                  self._conn_dropped(r, a))
                        if self._death_poisoning() else None)
                else:
                    c = RpcClient(addr, timeout=self._op_timeout())
            except ConnectionLost as e:
                self._raise_peer_lost(rank, e, f"unreachable: {e}")
            self._clients[rank] = c
            self._client_mode[rank] = want_py
        return c

    def _send(self, dst: int, key: tuple, payload):
        full_key = self._full_key(key, self.rank)
        if dst == self.rank:
            self._worker.col_push_local(full_key, payload)
            return
        try:
            if self._pipelined():
                self._seg_count += 1
                self._client(dst).push_parts(
                    "col_push_frame", {"key": full_key},
                    ser.serialize_parts(payload), pool=self.name)
            else:
                self._client(dst).call("col_push", key=full_key,
                                       data=payload)
        except ConnectionLost as e:
            self._raise_peer_lost(dst, e, f"send failed: {e}")

    def _push_frame(self, dst: int, key: tuple, parts):
        """One-way pre-framed send (hot path: ring segments, forwarded
        frames). `parts` is a serialize_parts list or [frame_view]."""
        full_key = self._full_key(key, self.rank)
        self._seg_count += 1
        try:
            self._client(dst).push_parts("col_push_frame",
                                         {"key": full_key},
                                         parts, pool=self.name)
        except ConnectionLost as e:
            self._raise_peer_lost(dst, e, f"send failed: {e}")

    def _shm_ok(self, dst: int) -> bool:
        """Segments to `dst` may ride the node's shm store: enabled, and
        the peer reports the same node_id (one cached col_meta round per
        peer). A TRANSIENT meta failure is negative-cached with a TTL —
        permanently pinning a same-node peer to the ~4x-slower socket
        path over one startup blip would be silent and unrecoverable."""
        import time as _time

        from ray_tpu._private.config import get_config

        if not get_config("collective_shm"):
            return False
        cached = self._peer_nodes.get(dst)
        if isinstance(cached, tuple):        # (None, retry_at): failed meta
            if _time.monotonic() < cached[1]:
                return False
            cached = None
        if cached is None:
            try:
                meta = self._client(dst).call("col_meta", timeout=30.0)
                cached = meta.get("node_id")
                self._peer_nodes[dst] = cached
            except Exception:
                self._peer_nodes[dst] = (None, _time.monotonic() + 30.0)
                return False
        return cached == self._worker.node_id

    # below this, the shm put/pin round costs more than just writing the
    # bytes to the socket — tiny segments and barrier tokens stay on TCP
    _SHM_MIN_BYTES = 64 * 1024

    def _push_seg(self, dst: int, key: tuple, seg: np.ndarray,
                  wire: _wire.WireCodec | None = None,
                  sync_into: np.ndarray | None = None, slot=None):
        """Send one ring segment, quantizing it first when `wire` is
        armed (per-segment: the codec may decline and the segment then
        travels exact — receivers detect the header tag, no
        negotiation). `sync_into` is the cross-rank-consistency hook:
        the DEQUANTIZED values are written there, so the rank that owns
        a chunk's final reduction keeps exactly the bytes every peer
        will decode (pass the segment itself to dequantize in place).
        `slot` pins the encoding to a per-slot arena and RETURNS the
        wire tuple, letting the pairwise exchange reduce against its
        own already-encoded send instead of decoding it back."""
        enc = wire.encode(seg, slot=slot) if wire is not None else None
        if enc is not None:
            if _tm.ENABLED and not self._quant_samples:
                # one sampled (prefix-bounded) segment per op
                self._quant_samples.append(
                    (wire.name, wire.sample_error(seg, enc)))
            if sync_into is not None:
                wire.decode(enc, out=sync_into)
            payload = enc
        else:
            if sync_into is not None and sync_into is not seg:
                np.copyto(sync_into, seg)
            payload = seg
        parts = ser.serialize_parts(payload)
        nbytes = ser.parts_size(parts)
        if _tm.ENABLED:
            fmt = wire.name if enc is not None else "off"
            self._wire_bytes[fmt] = self._wire_bytes.get(fmt, 0) + nbytes
        if nbytes >= self._SHM_MIN_BYTES and self._shm_ok(dst):
            full_key = self._full_key(key, self.rank)
            # group-tag(6) + epoch(4) + rank(2) + process counter(4) —
            # exactly the store's 16-byte id, unique across ranks (rank
            # byte-pair) and ops (low 4 counter bytes of the worker id
            # mint; no per-segment urandom syscall); the group tag lets
            # destroy sweep stranded segments whose notify never arrived
            # (worker_runtime.col_purge) and the epoch tag lets a rebuilt
            # gang sweep the DEAD incarnation's strays without touching
            # its own in-flight segments (col_set_epoch)
            oid = self._oid_prefix + self.rank.to_bytes(2, "big") \
                + self._worker._new_id()[12:]
            try:
                with _ma.tagged("collective_segment", group=self.name,
                                epoch=self.epoch, rank=self.rank):
                    nbytes = self._worker.store.put_ephemeral(oid, parts)
            except Exception:
                pass   # store full/unavailable: socket fallback below
            else:
                self._seg_count += 1
                try:
                    self._client(dst).push("col_push_shm", key=full_key,
                                           oid=oid, nbytes=nbytes)
                except ConnectionLost as e:
                    self._raise_peer_lost(dst, e, f"send failed: {e}")
                return enc
        self._push_frame(dst, key, parts)
        return enc

    def _forward(self, dst: int, key: tuple, frame,
                 wire: _wire.WireCodec | None = None):
        """Forward a received frame to the next ring hop without
        re-framing: a same-node shm frame travels as its object id
        (zero copy; the LAST hop deletes the object), anything else
        re-sends the received bytes. Consumes (releases) the frame.
        Under wire quantization this is the "quantize once per hop
        chain" guarantee — the already-quantized bytes travel on
        unchanged (`wire` is accounting-only here)."""
        if _tm.ENABLED:
            fmt = wire.name if wire is not None else "off"
            self._wire_bytes[fmt] = self._wire_bytes.get(fmt, 0) \
                + int(frame.nbytes)
        if isinstance(frame, _ShmFrame) and self._shm_ok(dst):
            full_key = self._full_key(key, self.rank)
            self._seg_count += 1
            # unpin BEFORE the next hop learns the oid: every caller has
            # already copied the bytes out, and notifying first opens a
            # race where the LAST hop's delete lands while this pin is
            # still held — store_delete returns ERR_IN_USE, the
            # best-effort delete drops, and the segment strands (the
            # test_shm_segment_transport_oracle flake)
            oid, nbytes = frame.oid, frame.nbytes
            frame.release(delete=False)
            try:
                self._client(dst).push("col_push_shm", key=full_key,
                                       oid=oid, nbytes=nbytes)
            except ConnectionLost as e:
                self._raise_peer_lost(dst, e, f"send failed: {e}")
            return
        self._push_frame(dst, key, [frame.view])
        frame.release()

    def _take(self, src: int, key: tuple, timeout: float | None = None):
        # Timeout is the failure detector of last resort (the
        # NCCL-watchdog analog): a dropped one-way frame makes the op
        # raise instead of hanging forever; a DEAD member usually beats
        # it by poisoning the group (col_take raises
        # CollectiveGroupError the moment the poison lands).
        # seq_pos=3: every op keys as (group, epoch, phase, seq, *step,
        # src), so the receiver validates the peer's op sequence and
        # raises a CollectiveSeqMismatchError on desync instead of
        # hanging.
        if timeout is None:
            timeout = self._op_timeout()
        return self._worker.col_take(self._full_key(key, src),
                                     timeout=timeout, seq_pos=3)

    def _recv_view(self, src: int, key: tuple,
                   timeout: float | None = None):
        """Take one message as (value, frame): frame-backed values view
        the receive buffer (transport frame or pinned shm segment)
        zero-copy; the CALLER must frame.release() after consuming
        (frame is None for legacy/local messages)."""
        msg = self._take(src, key, timeout)
        if isinstance(msg, ColShmRef):
            pin = self._worker.store.get(msg.oid)
            if pin is None:
                raise TimeoutError(
                    f"collective shm segment for {key} vanished from the "
                    f"store (evicted or deleted out of band)")
            frame = _ShmFrame(self._worker.store, msg.oid, pin)
            try:
                return ser.deserialize(frame.view), frame
            except BaseException:
                frame.release()   # or the pin would strand the segment
                raise
        if isinstance(msg, _protocol.OobFrame):
            try:
                return ser.deserialize(msg.view), msg
            except BaseException:
                msg.release()     # return the pooled buffer
                raise
        return msg, None

    def _recv(self, src: int, key: tuple, timeout: float | None = None):
        """Take one message as an OWNED value (safe to hand to callers:
        frame-backed arrays are copied out, the buffer goes back to the
        pool)."""
        val, frame = self._recv_view(src, key, timeout)
        if frame is not None:
            try:
                return _materialize(val)
            finally:
                frame.release()
        return val

    def _note_segs(self, op: str):
        n, self._seg_count = self._seg_count, 0
        wb, self._wire_bytes = self._wire_bytes, {}
        qs, self._quant_samples = self._quant_samples, []
        if not _tm.ENABLED:
            return
        if n:
            _tm.counter_inc("ray_tpu_collective_segments_total", float(n),
                            tags={"op": op, "group": self.name})
        for fmt, nbytes in wb.items():
            _tm.counter_inc("ray_tpu_collective_wire_bytes_total",
                            float(nbytes),
                            tags={"op": op, "group": self.name,
                                  "format": fmt})
        for fmt, ratio in qs:
            _tm.observe("ray_tpu_collective_quant_error_ratio", ratio,
                        tags={"op": op, "format": fmt})

    def _hierarchy_plan(self):
        """(local_ranks_on_my_host, one_leader_per_host) when the
        intra-host-first hierarchy applies, else None. Auto mode needs
        >1 host AND co-located ranks; "1" forces it (single-host tests
        exercise the degenerate one-leader ring)."""
        from ray_tpu._private.config import get_config

        mode = str(get_config("collective_hierarchy")).lower()
        if mode in ("0", "false", "off"):
            return None
        by_host: dict[str, list[int]] = {}
        for r in sorted(self.members):
            by_host.setdefault(str(self.members[r][0]), []).append(r)
        if mode not in ("1", "true", "force"):
            if len(by_host) < 2 or \
                    max(len(v) for v in by_host.values()) < 2:
                return None
        locals_ = next(v for v in by_host.values() if self.rank in v)
        leaders = sorted(v[0] for v in by_host.values())
        return locals_, leaders

    def close(self):
        # fail queued async handles fast (CollectiveGroupError naming the
        # teardown) before cutting the transport out from under them
        try:
            self._issue.close()
        except Exception:
            pass
        for c in self._clients.values():
            try:
                c.close()
            except Exception:
                pass
        self._clients.clear()

    # -- async op plane -----------------------------------------------------

    def submit_async(self, op: str, seq, thunk) -> CollectiveHandle:
        """Enqueue one collective op thunk onto this group's issue
        thread; ops execute strictly in submission order (the per-group
        seq order every rank already agrees on). The module-level API
        (`collective.allreduce_async`) submits telemetry-wrapped thunks
        through this."""
        return self._issue.submit(op, seq, thunk)

    def allreduce_async(self, arr: np.ndarray, op: str,
                        seq: int) -> CollectiveHandle:
        """Bare async allreduce (unit-test / embedded-group entry point;
        no telemetry wrapping). The caller must not mutate ``arr`` until
        the handle completes."""
        arr = np.asarray(arr)
        return self._issue.submit("allreduce", seq,
                                  lambda: self.allreduce(arr, op, seq))

    def reducescatter_async(self, arr: np.ndarray, op: str, seq: int,
                            wire_fmt=None) -> CollectiveHandle:
        arr = np.asarray(arr)
        return self._issue.submit(
            "reducescatter", seq,
            lambda: self.reducescatter(arr, op, seq, wire_fmt=wire_fmt))

    def allgather_async(self, arr, seq: int) -> CollectiveHandle:
        """Bare async allgather; resolves to the list of per-rank
        arrays. The caller must not mutate ``arr`` until the handle
        completes (the issue thread reads it at send time)."""
        return self._issue.submit("allgather", seq,
                                  lambda: self.allgather(arr, seq))

    def drain_async(self, timeout: float | None = None):
        """Barrier for mixed sync/async call sites: block until every
        async submission on this group completed. Synchronous module-API
        ops call this before touching group state, so a sync op issued
        after async ones keeps the submission order on the wire."""
        if self._issue.pending():
            self._issue.drain(timeout if timeout is not None
                              else self._op_timeout())

    # -- pipelined ring core ------------------------------------------------

    def _ring_allreduce(self, src: np.ndarray, acc: np.ndarray, op: str,
                        seq: int, ring: list[int], tag_r: str,
                        tag_g: str, wire: _wire.WireCodec | None = None):
        """Segmented pipelined ring allreduce over `ring` (a sorted list
        of member ranks; every participant passes the same list),
        reading this rank's contribution from `src` and assembling the
        full reduction into `acc` (src may alias acc). Classic
        2(m-1)-step ring, but each chunk moves as fixed-size segments
        over one-way frames: the reduced segment k of step s is
        forwarded as step s+1's segment k immediately — before step s
        touches segment k+1 — so the peer's transfer of the next
        segment overlaps this rank's reduce. The src/acc split avoids
        the upfront whole-array copy an in-place ring needs: every
        reduce reads the ORIGINAL contribution and writes acc, and each
        acc chunk is written exactly once (reduce-scatter) or copied in
        exactly once (allgather phase)."""
        m = len(ring)
        if m == 1:
            if acc is not src:
                np.copyto(acc, src)
            return
        fn = _OPS[op]
        pos = ring.index(self.rank)
        if m == 2:
            # pairwise exchange: one round instead of two. Each rank
            # pushes its full contribution segment-wise and reduces the
            # peer's locally — same bytes on the wire as the 2-ring,
            # half the notify->wake round trips on the critical path.
            return self._pair_allreduce(src, acc, fn, seq, ring, tag_r,
                                        wire)
        right, left = ring[(pos + 1) % m], ring[(pos - 1) % m]
        bounds = _split_bounds(acc.size, m)
        step = self._segment_elems(acc.itemsize)
        lo, hi = bounds[pos]
        for k, (a, b) in enumerate(_segments(lo, hi, step)):
            self._push_seg(right, (tag_r, seq, 0, k), src[a:b], wire)
        # reduce-scatter: after step s this rank holds the running
        # reduction of chunk (pos - s - 1); the final step leaves the
        # FULL reduction of chunk (pos + 1), which doubles as the
        # allgather phase's step-0 send.
        for s in range(m - 1):
            lo, hi = bounds[(pos - s - 1) % m]
            last = s == m - 2
            for k, (a, b) in enumerate(_segments(lo, hi, step)):
                seg = acc[a:b]
                incoming, frame = self._recv_view(left, (tag_r, seq, s, k))
                if wire is None:
                    fn(src[a:b], incoming, out=seg)
                else:
                    # fused dequantize-accumulate (wire implies sum)
                    wire.reduce_into(src[a:b], incoming, seg)
                if frame is not None:
                    frame.release()
                # the LAST reduce completes this chunk: decode our own
                # encoding back into acc (sync_into=seg) so this rank
                # holds the same post-quantization bytes every peer
                # will decode — rank-identical results despite the
                # lossy wire
                self._push_seg(right,
                               (tag_g, seq, 0, k) if last
                               else (tag_r, seq, s + 1, k), seg, wire,
                               sync_into=seg if (last and wire is not None)
                               else None)
        # allgather the reduced chunks around the ring (store-and-forward
        # per segment; forwarded segments reuse the received frame's
        # memory or shm object — no re-pickle, no copy, and under wire
        # quantization no re-quantization either)
        for s in range(m - 1):
            lo, hi = bounds[(pos - s) % m]
            for k, (a, b) in enumerate(_segments(lo, hi, step)):
                incoming, frame = self._recv_view(left, (tag_g, seq, s, k))
                if wire is None:
                    np.copyto(acc[a:b], incoming)
                else:
                    wire.copy_into(incoming, acc[a:b])
                if s < m - 2:
                    if frame is not None:
                        self._forward(right, (tag_g, seq, s + 1, k), frame,
                                      wire)
                    else:
                        # frame-less (local/legacy-shaped) delivery:
                        # acc already holds DECODED values — forward
                        # them EXACT (wire=None). Re-quantizing would
                        # mint a new int8 scale from the decoded data
                        # and downstream ranks would decode different
                        # bytes than the finishing rank holds,
                        # breaking the all-ranks-identical guarantee.
                        self._push_seg(right, (tag_g, seq, s + 1, k),
                                       acc[a:b])
                elif frame is not None:
                    frame.release()

    def _pair_allreduce(self, src: np.ndarray, acc: np.ndarray, fn, seq,
                        ring: list[int], tag: str,
                        wire: _wire.WireCodec | None = None):
        """2-member allreduce as a segmented full exchange. Operand
        order per chunk matches the 2-ring EXACTLY (bit-identical to
        the legacy path even for non-commutative corner cases like
        NaN-payload propagation): the chunk this rank owns in ring
        terms, bounds[pos], arrives pre-reduced as fn(peer, mine); the
        other chunk is reduced locally as fn(mine, peer).

        Wire quantization quantizes BOTH contributions: each rank
        retains its own per-segment encoding (slot arena) and the
        reduce is one fused acc = deq(mine) + deq(theirs) pass — both
        ranks add the identical decoded values, keeping the
        all-ranks-byte-identical property the ring gets from its
        final-chunk decode-back (finite data; NaN payload bits are not
        ordered under a lossy wire). Segments where either side's
        codec declined mix exact and decoded operands — same values,
        commutative order."""
        pos = ring.index(self.rank)
        peer = ring[1 - pos]
        bounds = _split_bounds(acc.size, 2)
        step = self._segment_elems(acc.itemsize)
        segs = _segments(0, acc.size, step)
        encs: list = []
        for k, (a, b) in enumerate(segs):
            encs.append(self._push_seg(peer, (tag, seq, 0, k), src[a:b],
                                       wire, slot=k))
        mlo, mhi = bounds[pos]
        for k, (a, b) in enumerate(segs):
            incoming, frame = self._recv_view(peer, (tag, seq, 0, k))
            if wire is not None:
                mine_enc = encs[k]
                inc_wire = _wire.is_wire(incoming)
                if mine_enc is not None and inc_wire:
                    wire.add_both(mine_enc, incoming, acc[a:b])
                elif mine_enc is not None:
                    # mine rode quantized, theirs exact: exact + deq —
                    # the peer computes the same two operands
                    wire.reduce_into(incoming, mine_enc, acc[a:b])
                elif inc_wire:
                    wire.reduce_into(src[a:b], incoming, acc[a:b])
                else:
                    # both exact (codec declined on both sides): the
                    # plain pairwise reduce below
                    self._pair_reduce_exact(src, acc, fn, incoming,
                                            a, b, bounds, pos, mlo, mhi)
                if frame is not None:
                    frame.release()
                continue
            # split the segment at the chunk boundary so each half gets
            # the ring's operand order
            self._pair_reduce_exact(src, acc, fn, incoming, a, b,
                                    bounds, pos, mlo, mhi)
            if frame is not None:
                frame.release()

    @staticmethod
    def _pair_reduce_exact(src, acc, fn, incoming, a, b, bounds, pos,
                           mlo, mhi):
        """Exact pairwise reduce of one received segment, with the
        2-ring's operand order per chunk half (bit-identical to the
        legacy path, NaN corners included)."""
        for lo, hi, mine_first in (
                (*bounds[1 - pos], True), (mlo, mhi, False)):
            s0, s1 = max(a, lo), min(b, hi)
            if s0 >= s1:
                continue
            inc = incoming[s0 - a:s1 - a]
            if mine_first:
                fn(src[s0:s1], inc, out=acc[s0:s1])
            else:
                fn(inc, src[s0:s1], out=acc[s0:s1])

    def _allreduce_hier(self, src: np.ndarray, acc: np.ndarray, op: str,
                        seq: int, locals_: list[int], leaders: list[int],
                        wire: _wire.WireCodec | None = None):
        """Intra-host reduce to the host leader, inter-host ring among
        leaders, intra-host broadcast back (result lands in acc). Wire
        quantization applies to the INTER-host leader ring only — the
        hr/hb hops below ride shm or loopback on the same host, where
        compressing costs more than the bytes are worth and exactness
        comes free."""
        fn = _OPS[op]
        leader = locals_[0]
        if self.rank != leader:
            self._push_seg(leader, ("hr", seq, 0, 0), src)
            incoming, frame = self._recv_view(leader, ("hb", seq, 0, 0))
            np.copyto(acc, incoming)
            if frame is not None:
                frame.release()
            return
        np.copyto(acc, src)
        for r in locals_[1:]:   # deterministic rank order
            incoming, frame = self._recv_view(r, ("hr", seq, 0, 0))
            fn(acc, incoming, out=acc)
            if frame is not None:
                frame.release()
        self._ring_allreduce(acc, acc, op, seq, leaders, "hra", "hga",
                             wire)
        for r in locals_[1:]:
            self._push_seg(r, ("hb", seq, 0, 0), acc)

    # -- collectives --------------------------------------------------------

    def allreduce(self, arr: np.ndarray, op: str, seq: int) -> np.ndarray:
        """Ring: reduce-scatter then allgather, 2(N-1) steps, each moving
        1/N of the data per step (bandwidth-optimal)."""
        n = self.world_size
        if n == 1:
            return arr
        if not self._pipelined():
            return self._allreduce_sync(arr, op, seq)
        flat = np.ascontiguousarray(arr).reshape(-1)
        wire = self._wire_ctx(flat.dtype, op)
        # owned result; src (the input) is only read, never copied up
        # front. Wire mode aligns the buffer so the quant kernels'
        # streaming-store fast path engages.
        acc = np.empty_like(flat) if wire is None \
            else _wire.aligned_empty(flat.size, flat.dtype)
        plan = self._hierarchy_plan()
        if plan is not None:
            self._allreduce_hier(flat, acc, op, seq, *plan, wire=wire)
        else:
            self._ring_allreduce(flat, acc, op, seq, list(range(n)),
                                 "ar", "ag", wire)
        self._note_segs("allreduce")
        return acc.reshape(arr.shape)

    def _allreduce_sync(self, arr: np.ndarray, op: str,
                        seq: int) -> np.ndarray:
        """Legacy synchronous ring (kill-switch path; the semantic
        reference the pipelined path must match bit-for-bit)."""
        n = self.world_size
        flat = np.ascontiguousarray(arr).reshape(-1)
        chunks = np.array_split(flat, n)
        fn = _OPS[op]
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        # reduce-scatter: after step s, rank owns the full reduction of
        # chunk (rank + 1) at the end
        for s in range(n - 1):
            send_idx = (self.rank - s) % n
            recv_idx = (self.rank - s - 1) % n
            self._send(right, ("ar", seq, s), chunks[send_idx])
            incoming = self._recv(left, ("ar", seq, s))
            chunks[recv_idx] = fn(chunks[recv_idx], incoming)
        # allgather the reduced chunks around the ring
        for s in range(n - 1):
            send_idx = (self.rank + 1 - s) % n
            recv_idx = (self.rank - s) % n
            self._send(right, ("ag", seq, s), chunks[send_idx])
            chunks[recv_idx] = self._recv(left, ("ag", seq, s))
        return np.concatenate(chunks).reshape(arr.shape)

    def reducescatter(self, arr: np.ndarray, op: str, seq: int,
                      wire_fmt=None) -> np.ndarray:
        n = self.world_size
        if n == 1:
            # the 1-way "shard" is the whole reduction: return the input
            # unchanged (shape intact), consistent with allreduce's n==1
            # behavior — NOT a flattened alias of the caller's array
            return arr
        if not self._pipelined():
            return self._reducescatter_sync(arr, op, seq)
        flat = np.ascontiguousarray(arr).reshape(-1)
        fn = _OPS[op]
        pos = self.rank
        bounds = _split_bounds(flat.size, n)
        step = self._segment_elems(flat.itemsize)
        wire = self._wire_ctx(flat.dtype, op, override=wire_fmt)
        if n == 2:
            # pairwise: each rank sends only the PEER's shard and
            # reduces its own as fn(theirs, mine) — half the traffic of
            # the ring+rotation, one round, and the exact operand order
            # the legacy path's final rotation delivers. (Each shard's
            # result lands on exactly one rank, so wire quantization
            # needs no decode-back for cross-rank consistency here.)
            peer = 1 - pos
            plo, phi = bounds[peer]
            for k, (a, b) in enumerate(_segments(plo, phi, step)):
                self._push_seg(peer, ("rs", seq, 0, k), flat[a:b], wire)
            mlo, mhi = bounds[pos]
            out = np.empty(mhi - mlo, dtype=flat.dtype) if wire is None \
                else _wire.aligned_empty(mhi - mlo, flat.dtype)
            for k, (a, b) in enumerate(_segments(mlo, mhi, step)):
                incoming, frame = self._recv_view(peer, ("rs", seq, 0, k))
                if wire is not None:
                    incoming = wire.maybe_decode(incoming)
                fn(incoming, flat[a:b], out=out[a - mlo:b - mlo])
                if frame is not None:
                    frame.release()
            self._note_segs("reducescatter")
            return out
        acc = np.empty_like(flat) if wire is None \
            else _wire.aligned_empty(flat.size, flat.dtype)
        right, left = (pos + 1) % n, (pos - 1) % n
        lo, hi = bounds[pos]
        for k, (a, b) in enumerate(_segments(lo, hi, step)):
            self._push_seg(right, ("rs", seq, 0, k), flat[a:b], wire)
        for s in range(n - 1):
            lo, hi = bounds[(pos - s - 1) % n]
            last = s == n - 2
            for k, (a, b) in enumerate(_segments(lo, hi, step)):
                seg = acc[a:b]
                incoming, frame = self._recv_view(left, ("rs", seq, s, k))
                if wire is None:
                    fn(flat[a:b], incoming, out=seg)
                else:
                    wire.reduce_into(flat[a:b], incoming, seg)
                if frame is not None:
                    frame.release()
                # after the last reduce this segment is fully reduced
                # chunk (pos+1): one final rotation puts chunk[pos]
                # everywhere (same "rsf" hop as the legacy path)
                self._push_seg(right,
                               ("rsf", seq, 0, k) if last
                               else ("rs", seq, s + 1, k), seg, wire)
        lo, hi = bounds[pos]
        out = np.empty(hi - lo, dtype=acc.dtype) if wire is None \
            else _wire.aligned_empty(hi - lo, acc.dtype)
        for k, (a, b) in enumerate(_segments(lo, hi, step)):
            incoming, frame = self._recv_view(left, ("rsf", seq, 0, k))
            if wire is None:
                np.copyto(out[a - lo:b - lo], incoming)
            else:
                wire.copy_into(incoming, out[a - lo:b - lo])
            if frame is not None:
                frame.release()
        self._note_segs("reducescatter")
        return out

    def _reducescatter_sync(self, arr: np.ndarray, op: str,
                            seq: int) -> np.ndarray:
        n = self.world_size
        flat = np.ascontiguousarray(arr).reshape(-1)
        chunks = np.array_split(flat, n)
        fn = _OPS[op]
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        for s in range(n - 1):
            send_idx = (self.rank - s) % n
            recv_idx = (self.rank - s - 1) % n
            self._send(right, ("rs", seq, s), chunks[send_idx])
            incoming = self._recv(left, ("rs", seq, s))
            chunks[recv_idx] = fn(chunks[recv_idx], incoming)
        # after N-1 steps this rank holds the full reduction of chunk
        # (rank + 1) % n; one final rotation puts chunk[rank] everywhere
        self._send(right, ("rsf", seq, 0), chunks[(self.rank + 1) % n])
        return self._recv(left, ("rsf", seq, 0))

    def allgather(self, arr, seq: int) -> list:
        n = self.world_size
        if n == 1:
            return [arr]
        if not self._pipelined():
            return self._allgather_sync(arr, seq)
        pos = self.rank
        right, left = (pos + 1) % n, (pos - 1) % n
        out: list = [None] * n
        out[pos] = arr
        # whole-array frames (per-rank shapes may differ, so hops are
        # not byte-segmented); one-way store-and-forward still pipelines
        # the ring, and forwarded hops reuse the received frame's bytes
        # (or pass the same shm object id on a shared node)
        self._push_seg(right, ("gat", seq, 0, 0), np.asarray(arr))
        for s in range(n - 1):
            recv_idx = (pos - s - 1) % n
            incoming, frame = self._recv_view(left, ("gat", seq, s, 0))
            out[recv_idx] = _materialize(incoming)
            if s < n - 2:
                if frame is not None:
                    self._forward(right, ("gat", seq, s + 1, 0), frame)
                else:
                    self._push_frame(right, ("gat", seq, s + 1, 0),
                                     ser.serialize_parts(
                                         np.asarray(incoming)))
            elif frame is not None:
                frame.release()
        self._note_segs("allgather")
        return out

    def _allgather_sync(self, arr, seq: int) -> list:
        n = self.world_size
        out: list = [None] * n
        out[self.rank] = arr
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        for s in range(n - 1):
            send_idx = (self.rank - s) % n
            recv_idx = (self.rank - s - 1) % n
            self._send(right, ("gat", seq, s), out[send_idx])
            out[recv_idx] = self._recv(left, ("gat", seq, s))
        return out

    def broadcast(self, arr, src: int, seq: int):
        """Binomial tree rooted at src: log2(N) rounds."""
        n = self.world_size
        if n == 1:
            return arr
        rel = (self.rank - src) % n
        value = arr if rel == 0 else None
        d = 1
        while d < n:
            d *= 2
        d //= 2
        while d >= 1:
            if rel % (2 * d) == 0 and rel + d < n:
                self._send((self.rank + d) % n, ("bc", seq, d), value)
            elif rel % (2 * d) == d:
                value = self._recv((self.rank - d) % n, ("bc", seq, d))
            d //= 2
        self._note_segs("broadcast")
        return value

    def reduce(self, arr: np.ndarray, dst: int, op: str, seq: int):
        """Binomial tree folding toward dst."""
        n = self.world_size
        if n == 1:
            return arr
        fn = _OPS[op]
        rel = (self.rank - dst) % n
        value = np.asarray(arr)
        d = 1
        while d < n:
            if rel % (2 * d) == d:
                self._send((self.rank - d) % n, ("rd", seq, d), value)
                self._note_segs("reduce")
                return arr  # non-dst ranks return their input unchanged
            if rel % (2 * d) == 0 and rel + d < n:
                incoming = self._recv((self.rank + d) % n, ("rd", seq, d))
                value = fn(value, incoming)
            d *= 2
        self._note_segs("reduce")
        return value if rel == 0 else arr

    def barrier(self, seq: int):
        """Dissemination barrier: ceil(log2 N) rounds of token exchange."""
        n = self.world_size
        d = 1
        while d < n:
            self._send((self.rank + d) % n, ("bar", seq, d), None)
            self._recv((self.rank - d) % n, ("bar", seq, d))
            d *= 2
        self._note_segs("barrier")

    def _p2p_wire_ctx(self, fmt, dtype) -> _wire.WireCodec | None:
        """Wire codec for one p2p hop, or None for the exact path.
        Unlike the ring's `_wire_ctx` there is no reduce, so eligibility
        is just float32 payloads on the pipelined path (bf16 is the
        classic inter-stage activation wire; int8 works too for
        activation tensors that tolerate it). `fmt` is per-CALL — the
        pipeline trainer passes its own knob — so p2p quantization never
        leaks into exact-by-contract users of the same group (the data
        plane's shuffle exchange, checkpoint gathers)."""
        fmt = _wire.normalize_format(fmt)
        if fmt is None:
            return None
        if not self._pipelined():
            return None   # legacy kill-switch path stays bit-exact
        if np.dtype(dtype) != np.float32:
            return None
        from ray_tpu._private.config import get_config

        block = int(get_config("collective_quant_block"))
        key = ("p2p", fmt, block)
        codec = self._wire_codecs.get(key)
        if codec is None:
            codec = self._wire_codecs[key] = _wire.WireCodec(fmt, block)
        return codec

    def send(self, arr, dst: int, seq: int, wire_fmt: str | None = None):
        wire = None
        if wire_fmt is not None and dst != self.rank \
                and isinstance(arr, np.ndarray) and arr.size:
            wire = self._p2p_wire_ctx(wire_fmt, arr.dtype)
        if dst == self.rank or not self._pipelined():
            # local delivery / legacy ring: original framing, and — like
            # the legacy segment path — no wire accounting
            self._send(dst, ("p2p", seq), arr)
            self._note_segs("send")
            return
        payload, fmt_name = arr, "off"
        if wire is not None:
            enc = wire.encode(np.ascontiguousarray(arr).reshape(-1))
            if enc is not None:
                # the encoding aliases codec scratch, which is safe:
                # push_parts writes the bytes to the socket before
                # returning, so the next encode can reuse the buffers
                payload = _wire.wrap_p2p(enc, arr.shape)
                fmt_name = wire.name
        # accounting mirrors the ring's _push_seg: every pipelined hop
        # records its SERIALIZED size under its format, exact hops under
        # "off" — so off-vs-quantized ratios read straight from
        # ray_tpu_collective_wire_bytes_total
        parts = ser.serialize_parts(payload)
        if _tm.ENABLED:
            self._wire_bytes[fmt_name] = \
                self._wire_bytes.get(fmt_name, 0) + ser.parts_size(parts)
        self._push_frame(dst, ("p2p", seq), parts)
        self._note_segs("send")

    def recv(self, src: int, seq: int):
        # a quantized p2p payload self-describes via its header — the
        # receiver needs no negotiation (and no codec when it's exact)
        return _wire.maybe_decode_p2p(self._recv(src, ("p2p", seq)))
