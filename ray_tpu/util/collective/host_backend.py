"""Peer-to-peer host collectives: ring allreduce/allgather/reducescatter,
binomial-tree broadcast/reduce, dissemination barrier.

Replaces round 1's single-rendezvous-actor data path (every tensor funnelled
through one process, O(world x bytes) on one socket) with direct
worker-to-worker transfers, the same topology class the reference's
NCCL/gloo groups use (nccl_collective_group.py rings, pygloo rings). The
named group actor now rendezvouses MEMBERSHIP ONLY (rank -> worker addr);
data rides each member CoreWorker's mailbox (worker_runtime.rpc_col_push).

All algorithms key messages by (group, op-seq, phase, step) so concurrent
ops and late arrivals never cross wires; collective calls must be issued in
the same order by every rank (standard collective contract, as NCCL).
"""
from __future__ import annotations

import numpy as np

from ray_tpu._private.protocol import RpcClient
from ray_tpu._private.worker_runtime import current_worker

_OPS = {
    "sum": np.add,
    "product": np.multiply,
    "min": np.minimum,
    "max": np.maximum,
}


class HostGroup:
    """This process's membership in one collective group."""

    def __init__(self, name: str, world_size: int, rank: int,
                 members: dict[int, tuple]):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.members = {int(r): tuple(a) for r, a in members.items()}
        self._clients: dict[int, RpcClient] = {}
        self._worker = current_worker()
        if self._worker is None:
            raise RuntimeError("collective group requires a ray_tpu worker "
                               "or driver runtime in this process")

    # -- plumbing -----------------------------------------------------------

    @staticmethod
    def _op_timeout() -> float:
        from ray_tpu._private.config import get_config

        return float(get_config("collective_op_timeout_s"))

    def _client(self, rank: int) -> RpcClient:
        c = self._clients.get(rank)
        if c is None or c.closed:
            c = RpcClient(self.members[rank], timeout=self._op_timeout())
            self._clients[rank] = c
        return c

    def _send(self, dst: int, key: tuple, payload):
        full_key = (self.name,) + key + (self.rank,)
        if dst == self.rank:
            self._worker.col_push_local(full_key, payload)
        else:
            self._client(dst).call("col_push", key=full_key, data=payload)

    def _recv(self, src: int, key: tuple, timeout: float | None = None):
        # Timeout doubles as the failure detector (the NCCL-watchdog analog):
        # a dead member makes the op raise instead of hanging forever.
        # seq_pos=2: every op keys as (group, phase, seq, *step, src), so
        # the receiver validates the peer's op sequence and raises a
        # CollectiveSeqMismatchError on desync instead of hanging.
        if timeout is None:
            timeout = self._op_timeout()
        return self._worker.col_take((self.name,) + key + (src,),
                                     timeout=timeout, seq_pos=2)

    def close(self):
        for c in self._clients.values():
            try:
                c.close()
            except Exception:
                pass
        self._clients.clear()

    # -- collectives --------------------------------------------------------

    def allreduce(self, arr: np.ndarray, op: str, seq: int) -> np.ndarray:
        """Ring: reduce-scatter then allgather, 2(N-1) steps, each moving
        1/N of the data per step (bandwidth-optimal)."""
        n = self.world_size
        if n == 1:
            return arr
        flat = np.ascontiguousarray(arr).reshape(-1)
        chunks = np.array_split(flat, n)
        fn = _OPS[op]
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        # reduce-scatter: after step s, rank owns the full reduction of
        # chunk (rank + 1) at the end
        for s in range(n - 1):
            send_idx = (self.rank - s) % n
            recv_idx = (self.rank - s - 1) % n
            self._send(right, ("ar", seq, s), chunks[send_idx])
            incoming = self._recv(left, ("ar", seq, s))
            chunks[recv_idx] = fn(chunks[recv_idx], incoming)
        # allgather the reduced chunks around the ring
        for s in range(n - 1):
            send_idx = (self.rank + 1 - s) % n
            recv_idx = (self.rank - s) % n
            self._send(right, ("ag", seq, s), chunks[send_idx])
            chunks[recv_idx] = self._recv(left, ("ag", seq, s))
        return np.concatenate(chunks).reshape(arr.shape)

    def reducescatter(self, arr: np.ndarray, op: str, seq: int) -> np.ndarray:
        n = self.world_size
        flat = np.ascontiguousarray(arr).reshape(-1)
        chunks = np.array_split(flat, n)
        if n == 1:
            return chunks[0]
        fn = _OPS[op]
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        for s in range(n - 1):
            send_idx = (self.rank - s) % n
            recv_idx = (self.rank - s - 1) % n
            self._send(right, ("rs", seq, s), chunks[send_idx])
            incoming = self._recv(left, ("rs", seq, s))
            chunks[recv_idx] = fn(chunks[recv_idx], incoming)
        # after N-1 steps this rank holds the full reduction of chunk
        # (rank + 1) % n; one final rotation puts chunk[rank] everywhere
        self._send(right, ("rsf", seq, 0), chunks[(self.rank + 1) % n])
        return self._recv(left, ("rsf", seq, 0))

    def allgather(self, arr: np.ndarray, seq: int) -> list:
        n = self.world_size
        if n == 1:
            return [arr]
        out: list = [None] * n
        out[self.rank] = arr
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        for s in range(n - 1):
            send_idx = (self.rank - s) % n
            recv_idx = (self.rank - s - 1) % n
            self._send(right, ("gat", seq, s), out[send_idx])
            out[recv_idx] = self._recv(left, ("gat", seq, s))
        return out

    def broadcast(self, arr, src: int, seq: int):
        """Binomial tree rooted at src: log2(N) rounds."""
        n = self.world_size
        if n == 1:
            return arr
        rel = (self.rank - src) % n
        value = arr if rel == 0 else None
        d = 1
        while d < n:
            d *= 2
        d //= 2
        while d >= 1:
            if rel % (2 * d) == 0 and rel + d < n:
                self._send((self.rank + d) % n, ("bc", seq, d), value)
            elif rel % (2 * d) == d:
                value = self._recv((self.rank - d) % n, ("bc", seq, d))
            d //= 2
        return value

    def reduce(self, arr: np.ndarray, dst: int, op: str, seq: int):
        """Binomial tree folding toward dst."""
        n = self.world_size
        if n == 1:
            return arr
        fn = _OPS[op]
        rel = (self.rank - dst) % n
        value = np.asarray(arr)
        d = 1
        while d < n:
            if rel % (2 * d) == d:
                self._send((self.rank - d) % n, ("rd", seq, d), value)
                return arr  # non-dst ranks return their input unchanged
            if rel % (2 * d) == 0 and rel + d < n:
                incoming = self._recv((self.rank + d) % n, ("rd", seq, d))
                value = fn(value, incoming)
            d *= 2
        return value if rel == 0 else arr

    def barrier(self, seq: int):
        """Dissemination barrier: ceil(log2 N) rounds of token exchange."""
        n = self.world_size
        d = 1
        while d < n:
            self._send((self.rank + d) % n, ("bar", seq, d), None)
            self._recv((self.rank - d) % n, ("bar", seq, d))
            d *= 2

    def send(self, arr, dst: int, seq: int):
        self._send(dst, ("p2p", seq), arr)

    def recv(self, src: int, seq: int):
        return self._recv(src, ("p2p", seq))
