"""Collective communication between actors/tasks.

API-equivalent to the reference's ray.util.collective
(/root/reference/python/ray/util/collective/collective.py —
init_collective_group :120, create_collective_group :151, allreduce :258,
allgather, reducescatter, broadcast, reduce, send :531, recv :594,
barrier) with TPU-native backends instead of NCCL/Gloo:

- "host": peer-to-peer ring/tree collectives between the member worker
  processes (host_backend.py). The named group actor rendezvouses
  MEMBERSHIP ONLY (rank -> worker address); tensor data moves directly
  between members' mailboxes — the same decentralised topology class as
  the reference's gloo/NCCL rings, never through a relay.
- "xla": the group becomes a jax.distributed process world and every op
  compiles to the XLA collective (psum / all_gather / psum_scatter) via
  shard_map over a Mesh spanning the group (xla_backend.py). On TPU these
  ride ICI; this is the SURVEY §5 retargeting of NCCL communicators.

Group lifecycle (advisor finding, round 1): the rendezvous actor is OWNED
by the group — destroy_collective_group kills it, and each group's
rendezvous state is namespaced by a per-creation nonce so two runs reusing
a group name (e.g. back-to-back Tune trials) can never see each other's
membership or in-flight state.

Semantics notes vs the reference: groups are named; ranks are dense
[0, world_size); ops are synchronous and return the result (functional,
jax-style) instead of mutating buffers in place.
"""
from __future__ import annotations

import threading

import numpy as np

import ray_tpu
from ray_tpu.util.collective import telemetry as _coltel


class _Rendezvous:
    """Named actor backing one collective group: membership exchange,
    incarnation epoch minting, and gang fault handling.

    Carries no tensor data (round 1's design funnelled all ranks' tensors
    through this actor; see host_backend.py for why that was replaced).

    Fault tolerance (gang FT PR): this actor is the one place that knows
    the full membership, so it is also the group's failure detector hub —
    it watches the GCS actor-death feed for member actors and POISONS the
    group on a death: every member's worker runtime gets a `col_poison`
    push, making pending and future collective takes raise a named
    CollectiveGroupError (dead rank included) well under the op timeout.
    It also mints the group's incarnation epoch (time-based, so a rebuilt
    group under the same name always gets a LARGER one): members stamp it
    into every col frame/shm notify, and ingest-side fencing rejects
    stale-epoch traffic from a dead incarnation."""

    def __init__(self, world_size: int, group_name: str = ""):
        import time as _time

        from ray_tpu.util.collective.telemetry import (
            GroupTimingAggregator,
        )

        self.world_size = world_size
        self.group_name = group_name
        self._cond = threading.Condition()
        self._members: dict[int, tuple] = {}
        self._actor_ids: dict[int, bytes] = {}
        self._epoch = 0
        # monotonic across incarnations: a rebuilt group's rendezvous
        # actor mints a strictly larger base than any predecessor's, so
        # epoch comparisons order incarnations correctly
        self._incarnation = _time.time_ns()
        self._poisoned: tuple | None = None   # (dead_ranks, reason)
        self._watch = None                    # ActorDeathWatch | ()
        self._watch_lock = threading.Lock()
        self._coordinator_port = None
        # eager, not lazy: all ranks' first timing flushes land ~one
        # flush interval after the group's first op, on CONCURRENT
        # actor threads (max_concurrency > 1) — a lazy check-then-set
        # here would let two threads build rival aggregators and lose
        # one side's records
        self._timing_agg = GroupTimingAggregator(world_size)

    def current_epoch(self) -> int:
        return self._incarnation + self._epoch

    # ------------------------------------------------------ fault handling

    def _ensure_death_watch(self):
        """Subscribe (once) to the GCS actor-lifecycle feed and poison
        the group when a member actor dies or is restarted out from
        under it. Config kill-switch: collective_death_poisoning
        (RAY_TPU_COLLECTIVE_DEATH_POISONING=0) falls back to op-timeout
        detection only."""
        if self._watch is not None:
            return
        with self._watch_lock:
            # every rank's join() races here at group creation (the actor
            # runs with max_concurrency > 1); unguarded, each loser of the
            # check-then-act leaks a GCS subscription + poll thread
            if self._watch is not None:
                return
            from ray_tpu._private.config import get_config

            if not get_config("collective_death_poisoning"):
                self._watch = ()
                return
            try:
                from ray_tpu._private.pubsub import watch_actor_deaths

                self._watch = watch_actor_deaths(self._on_member_death) or ()
            except Exception:
                self._watch = ()   # detection degraded to the op timeout

    def _on_member_death(self, actor_id, reason: str):
        with self._cond:
            dead = [r for r, a in self._actor_ids.items() if a == actor_id]
        if dead:
            self.poison(dead, f"member actor died ({reason})")

    def poison(self, dead_ranks, reason: str, epoch: int | None = None):
        """Poison the group: push col_poison to every surviving member's
        worker runtime (their pending col_take calls raise immediately).
        Called by the death watcher, or remotely by a member that
        directly observed a peer connection drop. Idempotent — the first
        record (naming the original culprit) wins. `epoch` guards a late
        report from a previous incarnation of a REBUILT group: stale
        reports are ignored."""
        with self._cond:
            # the staleness guard must share the lock with join()'s
            # incarnation reset: checked outside, a late report from the
            # dead incarnation could pass the guard, lose the race to a
            # concurrent rebuild, and poison the healthy successor gang.
            # Judge against _incarnation ALONE: a member holds the
            # current_epoch() of its join (>= _incarnation), but the
            # membership _epoch counter can bump after formation (rank
            # restart under a new addr) — comparing against the sum
            # would silently reject every existing member's live report
            if epoch is not None and epoch < self._incarnation:
                return False
            if self._poisoned is not None:
                return False
            dead_set = tuple(sorted(dead_ranks))
            self._poisoned = (dead_set, str(reason))
            members = dict(self._members)
            cur = self.current_epoch()
            self._cond.notify_all()   # wake blocked joiners
        # read the locals from here on: a concurrent rebuild's join()
        # may clear self._poisoned the moment the lock is released
        from ray_tpu._private import events as _events
        from ray_tpu._private.protocol import RpcClient

        _events.record("COLLECTIVE_GROUP_POISONED",
                       group=self.group_name,
                       dead_ranks=list(dead_set), reason=reason)
        # black box: capture the cluster's final collective spans while
        # survivors still buffer them (background — the poison pushes
        # below must not wait on a dump fan-out; debounced per process)
        try:
            from ray_tpu._private import flight_recorder as _fr

            _fr.trigger_dump("collective_poison", background=True)
        except Exception:
            pass
        survivors = []

        def _push(addr):
            try:
                c = RpcClient(tuple(addr), timeout=5.0)
                try:
                    c.push("col_poison", group=self.group_name,
                           dead_ranks=list(dead_set),
                           reason=str(reason), epoch=cur)
                finally:
                    c.close()
            except Exception:
                pass   # dead/unreachable member: its takes time out
        # fan out concurrently: a SECOND unreachable member's connect
        # retries must not stall the fast-path poison for the remaining
        # survivors (they'd keep blocking in col_take meanwhile)
        for rank, addr in members.items():
            if rank in dead_set:
                continue
            t = threading.Thread(target=_push, args=(addr,), daemon=True,
                                 name="col-poison-fanout")
            t.start()
            survivors.append(t)
        for t in survivors:
            t.join(timeout=6.0)
        return True

    def poisoned(self):
        with self._cond:
            return self._poisoned

    def report_timings(self, records: list):
        """Rank-timing ingest (fire-and-forget from members' flush
        threads): once every rank reported a (group, seq), the straggler
        detector runs here — the rendezvous actor is the only process
        that sees all ranks — and a COLLECTIVE_STRAGGLER event lands in
        this process's ring (picked up by list_cluster_events)."""
        if records:
            self._timing_agg.ingest(records)
        return True

    def join(self, rank: int, addr, timeout: float = 300.0,
             coordinator_port: int | None = None,
             actor_id: bytes | None = None):
        """Register and block until the full membership is present.
        Returns (members, coordinator_addr, incarnation_epoch)."""
        import time as _time

        self._ensure_death_watch()
        deadline = _time.time() + timeout
        with self._cond:
            if self._poisoned is not None:
                if self._members.get(rank) == tuple(addr):
                    # a member of the DOOMED incarnation itself (e.g. a
                    # survivor's lazy p2p join re-presenting the exact
                    # (rank, addr) it registered at group creation):
                    # fail fast with the poison record — resetting here
                    # would erase state surviving ranks still depend on
                    # and strand this joiner waiting for peers that are
                    # never coming
                    from ray_tpu import exceptions as _exc

                    raise _exc.CollectiveGroupError(
                        self.group_name, self._poisoned[0],
                        self._poisoned[1])
                # Unknown (rank, addr): a rebuilt gang under the same
                # name whose destroy never ran (e.g. every member died
                # at once, so no surviving worker could kill this
                # actor — rebuilt workers are new processes on new
                # ports). Every joiner PENDING at poison time was
                # already woken and failed (in-wait check below), so
                # reset to a fresh incarnation instead of bricking the
                # group name until max_failures exhausts.
                self._poisoned = None
                self._members = {}
                self._actor_ids = {}
                self._epoch += 1
                self._incarnation = _time.time_ns()
            if actor_id is not None:
                self._actor_ids[rank] = actor_id
            if rank in self._members and tuple(addr) != self._members[rank]:
                # a new worker took this rank (restart): new membership epoch
                self._epoch += 1
                self._members = {}
            if coordinator_port is not None and rank == 0:
                # rank 0 probed this port as free ON ITS HOST — the only
                # machine where "free" matters, since jax.distributed's
                # coordinator binds there (a port probed on the rendezvous
                # actor's host is wrong on a multi-host pod)
                self._coordinator_port = coordinator_port
            if self._coordinator_port is None:
                import socket

                s = socket.socket()
                s.bind(("0.0.0.0", 0))
                self._coordinator_port = s.getsockname()[1]
                s.close()
            while True:
                # (re-)register under the current epoch: an epoch reset by a
                # restarting peer wipes the table, so waiters must re-add
                # themselves before waiting again
                self._members[rank] = tuple(addr)
                if len(self._members) == self.world_size:
                    self._cond.notify_all()
                    break
                epoch = self._epoch
                ok = self._cond.wait_for(
                    lambda: (len(self._members) == self.world_size or
                             self._epoch != epoch or
                             self._poisoned is not None),
                    timeout=max(0.0, deadline - _time.time()))
                if self._poisoned is not None:
                    from ray_tpu import exceptions as _exc

                    raise _exc.CollectiveGroupError(
                        self.group_name, self._poisoned[0],
                        self._poisoned[1])
                if not ok:
                    raise TimeoutError(
                        f"collective group rendezvous timed out with "
                        f"{len(self._members)}/{self.world_size} ranks")
                if self._epoch == epoch and \
                        len(self._members) == self.world_size:
                    break
            host = self._members[0][0]
            return (dict(self._members),
                    f"{host}:{self._coordinator_port}",
                    self.current_epoch())


class _GroupState:
    def __init__(self, name, world_size, rank, backend, impl, store_handle,
                 epoch: int = 0):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.impl = impl              # HostGroup or XlaGroup
        self.store = store_handle     # rendezvous actor handle
        self.epoch = epoch            # incarnation epoch (fencing key)
        self.seq = 0
        self.p2p_seq: dict[tuple, int] = {}   # (src,dst) channel counters
        self.lock = threading.Lock()

    def next_seq(self):
        with self.lock:
            self.seq += 1
            return self.seq

    def next_p2p_seq(self, src, dst):
        """Sends/recvs pair on per-channel counters, independent of the
        collective-op sequence (a rank not involved in a p2p exchange must
        not affect its numbering)."""
        with self.lock:
            key = (src, dst)
            self.p2p_seq[key] = self.p2p_seq.get(key, 0) + 1
            return self.p2p_seq[key]


class GroupManager:
    """Per-process registry of joined groups (reference: collective.py:40)."""

    def __init__(self):
        self._groups: dict[str, _GroupState] = {}
        self._lock = threading.Lock()

    def create(self, group_name, world_size, rank, backend):
        if backend not in ("host", "xla"):
            raise ValueError(f"unknown backend {backend!r} "
                             "(TPU-native backends: 'host', 'xla')")
        from ray_tpu._private.worker_runtime import current_worker

        worker = current_worker()
        if worker is None:
            raise RuntimeError("init_collective_group requires ray_tpu to "
                               "be initialized in this process")
        store_cls = ray_tpu.remote(_Rendezvous)
        # +2 over world_size: during a join storm every member blocks one
        # actor thread in the rendezvous condvar; telemetry's
        # report_timings calls need their own headroom to drain
        handle = store_cls.options(
            name=f"_collective_{group_name}", get_if_exists=True,
            num_cpus=0, max_concurrency=max(world_size + 2, 4),
        ).remote(world_size, group_name)
        coord_port = None
        if rank == 0 and backend == "xla":
            import socket

            probe = socket.socket()
            probe.bind(("0.0.0.0", 0))
            coord_port = probe.getsockname()[1]
            probe.close()
        members, coordinator, epoch = ray_tpu.get(
            handle.join.remote(rank, worker.addr,
                               coordinator_port=coord_port,
                               actor_id=worker.actor_id), timeout=330.0)
        # arm ingest-side fencing BEFORE any peer can push: frames/shm
        # notifies stamped with an older incarnation's epoch are rejected
        # from here on, and the dead incarnation's strays are swept
        worker.col_set_epoch(group_name, epoch)

        if backend == "xla":
            from ray_tpu.util.collective.xla_backend import XlaGroup

            impl = XlaGroup(group_name, world_size, rank, coordinator)
        else:
            from ray_tpu.util.collective.host_backend import HostGroup

            impl = HostGroup(group_name, world_size, rank, members,
                             epoch=epoch, rendezvous=handle)
        state = _GroupState(group_name, world_size, rank, backend, impl,
                            handle, epoch)
        with self._lock:
            self._groups[group_name] = state
        return state

    def get(self, group_name) -> _GroupState:
        state = self._groups.get(group_name)
        if state is None:
            raise ValueError(
                f"collective group {group_name!r} not initialized in this "
                f"process — call init_collective_group first")
        return state

    def destroy(self, group_name):
        with self._lock:
            state = self._groups.pop(group_name, None)
        if state is None:
            return False
        try:
            state.impl.close()
        except Exception:
            pass
        # the lazily-built p2p HostGroup (xla groups route send/recv
        # through it) holds its own peer clients — with death-poisoning
        # on_close handlers attached, leaking them would let a LATER
        # peer exit poison a healthy successor group under this name
        host_p2p = getattr(state, "_host_p2p", None)
        if host_p2p is not None:
            try:
                host_p2p.close()
            except Exception:
                pass
        # purge this process's mailbox of the dead incarnation's
        # messages: a payload that landed after an op timeout would
        # otherwise masquerade as a NEWER seq to a re-created group
        # under the same name and trip its seq validation
        try:
            from ray_tpu._private.worker_runtime import current_worker

            worker = current_worker()
            if worker is not None:
                worker.col_purge(group_name)
        except Exception:
            pass
        # Kill the rendezvous actor so a future group under the same name
        # starts from clean state (advisor finding: the actor used to leak
        # and leak state across runs).
        try:
            ray_tpu.kill(state.store)
        except Exception:
            pass
        return True


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default"):
    """Join this process into a named collective group
    (reference: collective.py:120)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    return _manager.create(group_name, world_size, rank, backend)


def create_collective_group(actors, world_size: int, ranks: list[int],
                            backend: str = "host",
                            group_name: str = "default"):
    """Declarative setup from the driver (reference: collective.py:151):
    instructs each actor to join the group via an injected method call.
    Actors must expose `setup_collective_group(world_size, rank, backend,
    group_name)` or be created from a class using CollectiveActorMixin."""
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("need exactly one rank per actor == world_size")
    refs = [
        actor.setup_collective_group.remote(world_size, rank, backend,
                                            group_name)
        for actor, rank in zip(actors, ranks)
    ]
    return ray_tpu.get(refs)


class CollectiveActorMixin:
    """Inherit in actor classes that join groups declaratively."""

    def setup_collective_group(self, world_size, rank, backend, group_name):
        init_collective_group(world_size, rank, backend, group_name)
        return rank


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


def destroy_collective_group(group_name: str = "default"):
    return _manager.destroy(group_name)


def supports_async(group_name: str = "default") -> bool:
    """True when the group's backend can issue async ops
    (``allreduce_async``/``reducescatter_async``) — the host backend.
    Callers with a synchronous fallback (e.g. bucketed DDP) consult
    this instead of catching the submit-time ValueError."""
    return hasattr(_manager.get(group_name).impl, "submit_async")


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _manager.get(group_name)
        return True
    except ValueError:
        return False


# ------------------------------------------------------------------ ops

def _drain_pending(g: _GroupState):
    """Ordering barrier for mixed sync/async call sites: a synchronous
    op on a group with async handles in flight waits for the issue
    queue to empty first, so ops hit the wire in submission order and
    no two ops of this rank ever run concurrently on the group's
    state. One attribute probe + int check when async was never used."""
    drain = getattr(g.impl, "drain_async", None)
    if drain is not None:
        drain()


def _coerce(g, tensor):
    """Per-backend input coercion: the host backend moves host memory, so
    jax/torch arrays are fetched; the xla backend keeps jax arrays ON
    DEVICE end-to-end (its result is a device array too) and only
    converts foreign (torch/list) inputs."""
    is_jax = hasattr(tensor, "addressable_shards")
    if getattr(g, "backend", None) == "xla" and is_jax:
        return tensor
    if is_jax:
        return np.asarray(tensor)
    if hasattr(tensor, "detach"):
        return tensor.detach().cpu().numpy()
    return np.asarray(tensor)


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    """In the reference (collective.py:258) this mutates in place via NCCL;
    here the reduced array is returned (functional, jax-style)."""
    g = _manager.get(group_name)
    _drain_pending(g)
    arr = _coerce(g, tensor)
    seq = g.next_seq()
    return _coltel.run_op(g, "allreduce", seq,
                          lambda: g.impl.allreduce(arr, op, seq),
                          payload=arr)


def _submit_async(g: _GroupState, op: str, arr, body) -> object:
    submit = getattr(g.impl, "submit_async", None)
    if submit is None:
        raise ValueError(
            f"async collective ops require the host backend "
            f"(group {g.name!r} uses {g.backend!r})")
    seq = g.next_seq()
    # telemetry-wrapped: the op body executes on the group's issue
    # thread, so run_op's span/metric/rank-timing planes all apply and
    # step-anatomy records the comm interval as BACKGROUND (run_op
    # stamps `blocking` iff the op ran on the thread driving the step
    # loop — the async-DDP hook PR 11 left ready)
    return submit(op, seq,
                  lambda: _coltel.run_op(g, op, seq,
                                         lambda: body(seq), payload=arr))


def allreduce_async(tensor, group_name: str = "default", op: str = "sum"):
    """Start an allreduce and return a ``CollectiveHandle`` immediately
    (``wait(timeout)`` / ``poll()`` / ``result()``). Ops issue onto a
    per-group background issue thread in submission order, so every
    rank still sees the same op sequence; the caller must not mutate
    ``tensor`` until the handle completes. A poisoned group (member
    death, PR 5) fails pending handles fast with
    ``CollectiveGroupError``. Host backend only."""
    g = _manager.get(group_name)
    arr = _coerce(g, tensor)
    return _submit_async(g, "allreduce", arr,
                         lambda seq: g.impl.allreduce(arr, op, seq))


def reducescatter_async(tensor, group_name: str = "default",
                        op: str = "sum", wire_dtype: str | None = None):
    """Async reducescatter: each rank's handle resolves to its rank-th
    chunk of the reduction. Same contract as ``allreduce_async``.
    ``wire_dtype`` ("bf16"/"int8") opts THIS op's ring segments into
    wire quantization (same eligibility rules as the config knob:
    float32 sum, pipelined path) — sharded DDP uses it for per-bucket
    opt-in without flipping the group-wide knob."""
    g = _manager.get(group_name)
    arr = _coerce(g, tensor)
    return _submit_async(
        g, "reducescatter", arr,
        lambda seq: g.impl.reducescatter(arr, op, seq,
                                         wire_fmt=wire_dtype)
        if wire_dtype is not None else g.impl.reducescatter(arr, op, seq))


def allgather_async(tensor, group_name: str = "default"):
    """Async allgather: the handle resolves to the list of per-rank
    arrays (this rank's entry is the input, not a copy). Same handle
    contract as ``allreduce_async`` — submission-order issue thread,
    poison fast-fail, host backend only. Sharded DDP rides this to
    gather updated param shards while later buckets are still applying."""
    g = _manager.get(group_name)
    arr = _coerce(g, tensor)
    return _submit_async(g, "allgather", arr,
                         lambda seq: g.impl.allgather(arr, seq))


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "sum"):
    g = _manager.get(group_name)
    _drain_pending(g)
    arr = _coerce(g, tensor)
    seq = g.next_seq()
    return _coltel.run_op(g, "reduce", seq,
                          lambda: g.impl.reduce(arr, dst_rank, op, seq),
                          payload=arr)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _manager.get(group_name)
    _drain_pending(g)
    arr = _coerce(g, tensor)
    seq = g.next_seq()
    return _coltel.run_op(g, "broadcast", seq,
                          lambda: g.impl.broadcast(arr, src_rank, seq),
                          payload=arr)


def allgather(tensor, group_name: str = "default") -> list:
    g = _manager.get(group_name)
    _drain_pending(g)
    arr = _coerce(g, tensor)
    seq = g.next_seq()
    return _coltel.run_op(g, "allgather", seq,
                          lambda: g.impl.allgather(arr, seq),
                          payload=arr)


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    """Each rank gets the rank-th equal chunk of the reduction."""
    g = _manager.get(group_name)
    _drain_pending(g)
    arr = _coerce(g, tensor)
    seq = g.next_seq()
    return _coltel.run_op(g, "reducescatter", seq,
                          lambda: g.impl.reducescatter(arr, op, seq),
                          payload=arr)


def send(tensor, dst_rank: int, group_name: str = "default",
         wire_dtype: str | None = None):
    """``wire_dtype`` ("bf16"/"int8", default off) quantizes THIS hop's
    payload on the wire when it is an eligible float32 array — the
    classic inter-stage activation trick the pipeline trainer uses; the
    receiver detects the header and decodes, no negotiation. Exact by
    default; per-call opt-in so exact-by-contract users of the same
    group are never affected."""
    g = _manager.get(group_name)
    _drain_pending(g)
    arr = (_coerce(g, tensor) if getattr(g, "backend", None) != "xla"
           else np.asarray(tensor))
    seq = g.next_p2p_seq(g.rank, dst_rank)
    # p2p seq is per-channel, not group-wide: no straggler record
    # (seq=None), but latency/bytes metrics and spans still apply
    _coltel.run_op(g, "send", None,
                   lambda: _p2p(g).send(arr, dst_rank, seq,
                                        wire_fmt=wire_dtype),
                   payload=arr)


def recv(src_rank: int, group_name: str = "default"):
    """Unlike the reference (which writes into a passed buffer), returns the
    received array."""
    g = _manager.get(group_name)
    _drain_pending(g)
    seq = g.next_p2p_seq(src_rank, g.rank)
    return _coltel.run_op(g, "recv", None,
                          lambda: _p2p(g).recv(src_rank, seq),
                          measure_result=True)


def send_device(tensor, dst_rank: int, group_name: str = "default"):
    """Device-resident point-to-point send (xla groups only): the
    endpoints enter a compiled 2-device ppermute program, so on TPU the
    payload rides ICI/DCN instead of the host mailbox plane (the
    NCCL-send analog the host-path `send` is not). Matched-call
    contract: the peer must call `recv_device` with the same shape/dtype
    in the same order."""
    g = _manager.get(group_name)
    if getattr(g, "backend", None) != "xla":
        raise ValueError("send_device requires an xla collective group")
    # _coerce keeps jax arrays ON DEVICE for xla groups and converts
    # foreign inputs (torch tensors incl. requires_grad, lists)
    arr = _coerce(g, tensor)
    _coltel.run_op(g, "send_device", None,
                   lambda: g.impl.send_device(arr, dst_rank),
                   payload=arr)


def recv_device(shape, dtype, src_rank: int, group_name: str = "default"):
    """Device-resident point-to-point receive (pairs with send_device);
    returns a device-resident jax array."""
    g = _manager.get(group_name)
    if getattr(g, "backend", None) != "xla":
        raise ValueError("recv_device requires an xla collective group")
    return _coltel.run_op(g, "recv_device", None,
                          lambda: g.impl.recv_device(shape, dtype,
                                                     src_rank),
                          measure_result=True)


def barrier(group_name: str = "default"):
    g = _manager.get(group_name)
    _drain_pending(g)
    seq = g.next_seq()
    _coltel.run_op(g, "barrier", seq, lambda: g.impl.barrier(seq))


def _p2p(g: _GroupState):
    """p2p plane: host mailboxes for both backends (an SPMD program cannot
    express a two-party exchange; the reference's p2p likewise bypasses the
    collective rings)."""
    if g.backend == "host":
        return g.impl
    host = getattr(g, "_host_p2p", None)
    if host is None:
        from ray_tpu.util.collective.host_backend import HostGroup

        members, _, epoch = ray_tpu.get(g.store.join.remote(
            g.rank, _current_addr()), timeout=330.0)
        host = HostGroup(g.name, g.world_size, g.rank, members,
                         epoch=epoch, rendezvous=g.store)
        g._host_p2p = host
    return host


def _current_addr():
    from ray_tpu._private.worker_runtime import current_worker

    return current_worker().addr


def allgather_object(obj, group_name: str = "default") -> list:
    """Gather arbitrary picklable objects from every rank (reference:
    collective.py allgather_object / torch.distributed.all_gather_object):
    pickle → uint8 tensor padded to the max length → allgather → unpickle."""
    import pickle

    import numpy as np

    blob = np.frombuffer(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL), dtype=np.uint8)
    n = np.array([len(blob)], dtype=np.int64)
    sizes = [int(s[0]) for s in allgather(n, group_name)]
    padded = np.zeros(max(sizes), dtype=np.uint8)
    padded[: len(blob)] = blob
    gathered = allgather(padded, group_name)
    return [pickle.loads(np.asarray(g)[:size].tobytes())
            for g, size in zip(gathered, sizes)]


def broadcast_object(obj, src_rank: int = 0,
                     group_name: str = "default"):
    """Broadcast one picklable object from src_rank to every rank."""
    import pickle

    import numpy as np

    me = get_rank(group_name)
    if me == src_rank:
        blob = np.frombuffer(
            pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
            dtype=np.uint8)
        n = np.array([len(blob)], dtype=np.int64)
    else:
        blob = None
        n = np.zeros(1, dtype=np.int64)
    n = np.asarray(broadcast(n, src_rank, group_name))
    size = int(n[0])
    payload = (blob if me == src_rank
               else np.zeros(size, dtype=np.uint8))
    payload = np.asarray(broadcast(payload, src_rank, group_name))
    if me == src_rank:
        return obj
    return pickle.loads(payload[:size].tobytes())
