"""Collective communication between actors/tasks.

API-equivalent to the reference's ray.util.collective
(/root/reference/python/ray/util/collective/collective.py —
init_collective_group :120, create_collective_group :151, allreduce :258,
allgather, reducescatter, broadcast, reduce, send :531, recv :594,
barrier) with TPU-native backends instead of NCCL/Gloo:

- "host": cross-process collectives relayed through a rendezvous actor
  (the analog of the reference's gloo CPU backend and of its NCCL
  unique-id rendezvous via a named actor, nccl_collective_group.py:29-75).
  Correct anywhere the runtime runs; bandwidth-bound by the object store.
- "xla": members are jax processes forming one global device mesh; the ops
  compile to ICI collectives (psum/all_gather/reduce_scatter/ppermute)
  inside jit. Group creation materializes a jax.sharding.Mesh over the
  member processes' chips (multi-host via jax.distributed). On-host
  collectives inside ONE process should use the mesh directly
  (ray_tpu.parallel.mesh); this layer exists for the actor-world.

Semantics notes vs the reference: groups are named; ranks are dense
[0, world_size); ops are synchronous (the reference's cupy-stream async
semantics don't apply — XLA programs and host relays both complete before
returning).
"""
from __future__ import annotations

import threading

import numpy as np

import ray_tpu
from ray_tpu._private import api as _api

_REDUCE_OPS = {
    "sum": lambda arrs: _tree_reduce(arrs, np.add),
    "product": lambda arrs: _tree_reduce(arrs, np.multiply),
    "min": lambda arrs: _tree_reduce(arrs, np.minimum),
    "max": lambda arrs: _tree_reduce(arrs, np.maximum),
}


def _tree_reduce(arrs, op):
    out = arrs[0]
    for a in arrs[1:]:
        out = op(out, a)
    return out


class _RendezvousStore:
    """Named actor backing one collective group: mailbox + phased gather.

    Runs anywhere; methods are called concurrently by all ranks, each in its
    own handler thread, synchronized on conditions (this leans on the actor
    runtime executing different callers' methods concurrently)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self._cond = threading.Condition()
        self._gathers: dict = {}      # (seq, tag) -> {rank: value}
        self._results: dict = {}      # (seq, tag) -> reduced value
        self._mailbox: dict = {}      # (seq, src, dst) -> value
        self._done_count: dict = {}

    def gather_compute(self, seq, tag, rank, value, op):
        """All-gather contributions; when complete, compute `op` once and
        hand every rank the result."""
        key = (seq, tag)
        with self._cond:
            self._gathers.setdefault(key, {})[rank] = value
            if len(self._gathers[key]) == self.world_size:
                vals = [self._gathers[key][r]
                        for r in range(self.world_size)]
                if op == "gather":
                    self._results[key] = vals
                else:
                    self._results[key] = _REDUCE_OPS[op](vals)
                self._cond.notify_all()
            else:
                self._cond.wait_for(lambda: key in self._results,
                                    timeout=300.0)
                if key not in self._results:
                    raise TimeoutError(
                        f"collective {tag} seq={seq} timed out waiting for "
                        f"{self.world_size - len(self._gathers[key])} ranks")
            result = self._results[key]
            self._done_count[key] = self._done_count.get(key, 0) + 1
            if self._done_count[key] == self.world_size:
                del self._gathers[key], self._results[key]
                del self._done_count[key]
            return result

    def send(self, seq, src, dst, value):
        with self._cond:
            self._mailbox[(seq, src, dst)] = value
            self._cond.notify_all()

    def recv(self, seq, src, dst):
        key = (seq, src, dst)
        with self._cond:
            self._cond.wait_for(lambda: key in self._mailbox, timeout=300.0)
            if key not in self._mailbox:
                raise TimeoutError(f"recv from rank {src} timed out")
            return self._mailbox.pop(key)


class _GroupState:
    def __init__(self, name, world_size, rank, backend, store_handle):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.store = store_handle
        self.seq = 0
        self.p2p_seq: dict[tuple, int] = {}   # (src,dst) channel counters
        self.lock = threading.Lock()

    def next_seq(self):
        with self.lock:
            self.seq += 1
            return self.seq

    def next_p2p_seq(self, src, dst):
        """Sends/recvs pair on per-channel counters, independent of the
        collective-op sequence (a rank not involved in a p2p exchange must
        not affect its numbering)."""
        with self.lock:
            key = (src, dst)
            self.p2p_seq[key] = self.p2p_seq.get(key, 0) + 1
            return self.p2p_seq[key]


class GroupManager:
    """Per-process registry of joined groups (reference: collective.py:40)."""

    def __init__(self):
        self._groups: dict[str, _GroupState] = {}
        self._lock = threading.Lock()

    def create(self, group_name, world_size, rank, backend):
        if backend not in ("host", "xla"):
            raise ValueError(f"unknown backend {backend!r} "
                             "(TPU-native backends: 'host', 'xla')")
        store_cls = ray_tpu.remote(_RendezvousStore)
        handle = store_cls.options(
            name=f"_collective_{group_name}", get_if_exists=True,
            num_cpus=0, max_concurrency=max(world_size, 2),
        ).remote(world_size)
        state = _GroupState(group_name, world_size, rank, backend, handle)
        with self._lock:
            self._groups[group_name] = state
        return state

    def get(self, group_name) -> _GroupState:
        state = self._groups.get(group_name)
        if state is None:
            raise ValueError(
                f"collective group {group_name!r} not initialized in this "
                f"process — call init_collective_group first")
        return state

    def destroy(self, group_name):
        with self._lock:
            state = self._groups.pop(group_name, None)
        return state is not None


_manager = GroupManager()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "host",
                          group_name: str = "default"):
    """Join this process into a named collective group
    (reference: collective.py:120)."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    return _manager.create(group_name, world_size, rank, backend)


def create_collective_group(actors, world_size: int, ranks: list[int],
                            backend: str = "host",
                            group_name: str = "default"):
    """Declarative setup from the driver (reference: collective.py:151):
    instructs each actor to join the group via an injected method call.
    Actors must expose `setup_collective_group(world_size, rank, backend,
    group_name)` or be created from a class using CollectiveActorMixin."""
    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("need exactly one rank per actor == world_size")
    refs = [
        actor.setup_collective_group.remote(world_size, rank, backend,
                                            group_name)
        for actor, rank in zip(actors, ranks)
    ]
    return ray_tpu.get(refs)


class CollectiveActorMixin:
    """Inherit in actor classes that join groups declaratively."""

    def setup_collective_group(self, world_size, rank, backend, group_name):
        init_collective_group(world_size, rank, backend, group_name)
        return rank


def get_rank(group_name: str = "default") -> int:
    return _manager.get(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _manager.get(group_name).world_size


def destroy_collective_group(group_name: str = "default"):
    return _manager.destroy(group_name)


def is_group_initialized(group_name: str = "default") -> bool:
    try:
        _manager.get(group_name)
        return True
    except ValueError:
        return False


# ------------------------------------------------------------------ ops

def _to_host(tensor):
    """jax/torch/numpy → numpy (host relay works on host memory)."""
    if hasattr(tensor, "device") and hasattr(tensor, "addressable_shards"):
        return np.asarray(tensor)   # jax array
    if hasattr(tensor, "detach"):
        return tensor.detach().cpu().numpy()
    return np.asarray(tensor)


def allreduce(tensor, group_name: str = "default", op: str = "sum"):
    """In the reference (collective.py:258) this mutates in place via NCCL;
    here the reduced array is returned (functional, jax-style)."""
    g = _manager.get(group_name)
    seq = g.next_seq()
    return ray_tpu.get(g.store.gather_compute.remote(
        seq, "allreduce", g.rank, _to_host(tensor), op))


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = "sum"):
    g = _manager.get(group_name)
    seq = g.next_seq()
    result = ray_tpu.get(g.store.gather_compute.remote(
        seq, "reduce", g.rank, _to_host(tensor), op))
    return result if g.rank == dst_rank else tensor


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _manager.get(group_name)
    seq = g.next_seq()
    contributions = ray_tpu.get(g.store.gather_compute.remote(
        seq, "broadcast", g.rank, _to_host(tensor) if g.rank == src_rank
        else None, "gather"))
    return contributions[src_rank]


def allgather(tensor, group_name: str = "default") -> list:
    g = _manager.get(group_name)
    seq = g.next_seq()
    return ray_tpu.get(g.store.gather_compute.remote(
        seq, "allgather", g.rank, _to_host(tensor), "gather"))


def reducescatter(tensor, group_name: str = "default", op: str = "sum"):
    """Each rank gets the rank-th equal chunk of the reduction."""
    g = _manager.get(group_name)
    seq = g.next_seq()
    reduced = ray_tpu.get(g.store.gather_compute.remote(
        seq, "reducescatter", g.rank, _to_host(tensor), op))
    chunks = np.array_split(reduced, g.world_size, axis=0)
    return chunks[g.rank]


def send(tensor, dst_rank: int, group_name: str = "default"):
    g = _manager.get(group_name)
    seq = g.next_p2p_seq(g.rank, dst_rank)
    ray_tpu.get(g.store.send.remote(seq, g.rank, dst_rank,
                                    _to_host(tensor)))


def recv(src_rank: int, group_name: str = "default"):
    """Unlike the reference (which writes into a passed buffer), returns the
    received array."""
    g = _manager.get(group_name)
    seq = g.next_p2p_seq(src_rank, g.rank)
    return ray_tpu.get(g.store.recv.remote(seq, src_rank, g.rank))


def barrier(group_name: str = "default"):
    g = _manager.get(group_name)
    seq = g.next_seq()
    ray_tpu.get(g.store.gather_compute.remote(
        seq, "barrier", g.rank, None, "gather"))
