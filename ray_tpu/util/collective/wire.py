"""Block-quantized wire formats for the pipelined host collectives.

The transport/user-dtype split (the object-store/transport boundary of
the original Ray paper) means the bytes a ring segment puts ON THE WIRE
don't have to be the bytes the caller handed in: EQuARX-style block
quantization sends each float32 segment as bf16 (2x smaller) or as int8
with per-block float32 scales (~4x smaller), recovering most of that
factor as effective bus bandwidth on the socket/shm hop. Selection is
per group op via ``collective_wire_dtype`` (env
``RAY_TPU_COLLECTIVE_WIRE_DTYPE=off|bf16|int8``, default ``off`` =
bit-exact legacy framing).

Wire frame: an eligible segment is replaced by a tagged tuple

    (_MAGIC, tag, nelems, *payload)          # tag: WIRE_BF16|WIRE_INT8
      bf16 payload: (q_uint16,)
      int8 payload: (block, scales_f32, q_int8, tail_f32)

serialized through the existing ``serialize_parts`` framing (the big
``q`` array rides an out-of-band buffer, zero-copy on both ends; the
header tag is what the ``wire-format`` raylint pass pins). Receivers
detect the magic per segment, so a sender may fall back to the exact
format for individual segments (non-finite int8 blocks, sub-block
tails) without any negotiation.

Numerics (pinned by tests/test_zz_quant_collectives.py, mirrored by
``src/quant/quant.cc``):

- **bf16**: round-to-nearest-even of the top 16 bits; NaN is truncated
  with the quiet bit forced (rounding a NaN mantissa could carry into
  the exponent and turn it into +-Inf), Inf is exact. Per element
  ``|deq(x) - x| <= 2**-8 * |x|``.
- **int8**: per-block ``scale = absmax/127``; ``|deq(x) - x| <=
  absmax_block/254`` (half a step; the native kernel rounds half away
  from zero, the numpy fallback half to even — both within the bound).
  Blocks with ``absmax < 1.2e-36`` (subnormal territory, where
  ``1/scale`` overflows) encode as zeros; a block containing Inf/NaN
  makes the WHOLE segment fall back to the exact format. The sub-block
  tail (``nelems % block``) always travels as exact float32.

Fast path: ``librayquant.so`` (built on demand like the store/rpc
cores) fuses each direction into one vectorized pass, including a
dequantize-ACCUMULATE used by the ring's reduce step. The numpy
fallback is semantically identical, just slower.
"""
from __future__ import annotations

import ctypes
import threading

import numpy as np

# Wire-format tags, one per segment header. Pinned by the wire-format
# raylint pass (RTW305) and tests/test_protocol.py: every group member
# parses peers' segment headers by these values, so renumbering them is
# a wire-protocol change (bump PROTOCOL_VERSION if you ever must).
WIRE_OFF, WIRE_BF16, WIRE_INT8 = 0, 1, 2

# config value -> tag (``off`` deliberately absent: it means "no wire
# codec at all", not a codec that tags frames WIRE_OFF)
WIRE_FORMATS = {"bf16": WIRE_BF16, "int8": WIRE_INT8}

# spellings that mean "exact wire" — ONE list, shared by every layer
# that resolves a wire-format knob (ring ctx, p2p ctx, PipelineConfig)
OFF_ALIASES = ("", "off", "0", "false", "none")


def normalize_format(fmt) -> str | None:
    """Canonicalize a wire-format knob value: None for the exact path
    (None or any OFF_ALIASES spelling), the lowercase format name for a
    known format, ValueError otherwise — so a typo fails at the
    RESOLVING layer (config read, PipelineConfig construction) instead
    of deep inside a worker's first send."""
    if fmt is None:
        return None
    f = str(fmt).strip().lower()
    if f in OFF_ALIASES:
        return None
    if f not in WIRE_FORMATS:
        raise ValueError(
            f"wire dtype {fmt!r}: expected one of off, "
            f"{', '.join(sorted(WIRE_FORMATS))}")
    return f

# header sentinel: first element of every quantized-segment tuple
_MAGIC = "rtqw1"

# point-to-point wrapper sentinel: a quantized p2p payload travels as
# (P2P_MAGIC, shape, <wire tuple>) so the receiver can restore the
# original array shape (ring segments are always flat; p2p hops are
# whole arrays). Receivers detect the header per message — a sender may
# fall back to the exact path (ineligible dtype, codec declined) with
# no negotiation, exactly like the segment wire.
P2P_MAGIC = "rtqp2pw1"

# int8 blocks whose absmax sits below this encode as zeros: the
# reciprocal scale would overflow float32 (absmax/127 < ~1/FLT_MAX) and
# the absolute error of flushing is < 1.2e-36 — unobservable next to
# either format's quantization step
_I8_TINY = 1.2e-36

_lib = None
_lib_failed = False
_lib_lock = threading.Lock()
_force_numpy = False    # test hook: exercises the fallback kernels


def _native():
    """librayquant.so, lazily built/loaded; None -> numpy fallback."""
    global _lib, _lib_failed
    if _force_numpy or _lib_failed:
        return None
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None or _lib_failed:
            return None if _lib_failed else _lib
        try:
            from ray_tpu._private.native_build import ensure_lib

            lib = ctypes.CDLL(ensure_lib("rayquant"))
            I64, P = ctypes.c_int64, ctypes.c_void_p
            lib.rq_enc_i8.restype = ctypes.c_int
            lib.rq_enc_i8.argtypes = [P, I64, I64, P, P]
            lib.rq_dec_i8.restype = None
            lib.rq_dec_i8.argtypes = [P, P, I64, P, I64]
            lib.rq_dec_add_i8.restype = None
            lib.rq_dec_add_i8.argtypes = [P, P, I64, P, P, I64]
            lib.rq_enc_bf16.restype = None
            lib.rq_enc_bf16.argtypes = [P, I64, P]
            lib.rq_dec_bf16.restype = None
            lib.rq_dec_bf16.argtypes = [P, I64, P]
            lib.rq_dec_add_bf16.restype = None
            lib.rq_dec_add_bf16.argtypes = [P, P, P, I64]
            lib.rq_add_qq_i8.restype = None
            lib.rq_add_qq_i8.argtypes = [P, P, P, P, I64, P, I64]
            lib.rq_add_qq_bf16.restype = None
            lib.rq_add_qq_bf16.argtypes = [P, P, P, I64]
            _lib = lib
        except Exception:
            _lib_failed = True
            return None
    return _lib


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def is_wire(val) -> bool:
    """Is `val` a quantized-segment wire tuple?"""
    return isinstance(val, tuple) and len(val) >= 3 and val[0] == _MAGIC


def aligned_empty(n: int, dtype, align: int = 64) -> np.ndarray:
    """Uninitialized 1-D array whose data pointer is `align`-byte
    aligned. numpy only guarantees 16; the quant kernels' non-temporal
    store paths need 32 for the destination (they quietly fall back to
    regular stores otherwise), so wire-mode result buffers come from
    here."""
    itemsize = np.dtype(dtype).itemsize
    buf = np.empty(n * itemsize + align, np.uint8)
    off = (-buf.ctypes.data) % align
    return buf[off:off + n * itemsize].view(dtype)


class WireCodec:
    """One (format, block) quantization context for a HostGroup.

    Holds the reusable scratch buffers (encode output, decode output),
    so steady-state rings allocate nothing per segment; safe because a
    group's ops are serial (the collective contract) and every send
    completes before the next encode reuses the buffer. NOT thread-safe
    across concurrent ops on the same group — neither is the ring.
    """

    def __init__(self, fmt: str, block: int):
        if fmt not in WIRE_FORMATS:
            raise ValueError(
                f"unknown collective wire dtype {fmt!r}: expected one of "
                f"off, {', '.join(sorted(WIRE_FORMATS))}")
        self.name = fmt
        self.tag = WIRE_FORMATS[fmt]
        self.block = max(1, int(block))
        self._enc_scratch: dict[tuple, np.ndarray] = {}
        self._dec_scratch: dict[int, np.ndarray] = {}

    def _scratch(self, kind: str, shape: int, dtype) -> np.ndarray:
        key = (kind, shape, np.dtype(dtype).str)
        arr = self._enc_scratch.get(key)
        if arr is None:
            arr = self._enc_scratch[key] = np.empty(shape, dtype)
        return arr

    # ------------------------------------------------------------ encode

    def encode(self, seg: np.ndarray, slot=None):
        """Quantize one contiguous float32 segment; returns the wire
        tuple, or None when this segment must travel exact (int8 with
        non-finite data, or nothing to gain: all-tail int8 segments,
        sub-element sizes). The returned tuple aliases codec scratch and
        is valid until the next encode of the same size — UNLESS `slot`
        is given, which pins it to a per-slot arena so a caller can
        retain one encoding per ring segment (the pairwise exchange
        keeps its own sends alive to feed the fused add_both)."""
        n = seg.size
        if n == 0:
            return None
        if self.tag == WIRE_BF16:
            return self._enc_bf16(seg, n, slot)
        return self._enc_i8(seg, n, slot)

    def _enc_bf16(self, seg, n, slot=None):
        q = self._scratch(("q16", slot), n, np.uint16)
        lib = _native()
        if lib is not None:
            lib.rq_enc_bf16(_ptr(seg), n, _ptr(q))
        else:
            u = seg.view(np.uint32)
            rounded = (u + (((u >> 16) & np.uint32(1)) + np.uint32(0x7FFF))
                       ) >> np.uint32(16)
            np.copyto(q, rounded.astype(np.uint16))
            naninf = (u & np.uint32(0x7F800000)) == np.uint32(0x7F800000)
            if naninf.any():
                trunc = (u >> np.uint32(16)).astype(np.uint16)
                hasmant = (u & np.uint32(0x007FFFFF)) != 0
                trunc |= (naninf & hasmant).astype(np.uint16) << 6
                np.copyto(q, trunc, where=naninf)
        return (_MAGIC, WIRE_BF16, n, q)

    def _enc_i8(self, seg, n, slot=None):
        nb = n // self.block
        if nb == 0:
            return None   # all tail: exact fallback, nothing to gain
        nq = nb * self.block
        scales = self._scratch(("sc", slot), nb, np.float32)
        q = self._scratch(("q8", slot), nq, np.int8)
        lib = _native()
        if lib is not None:
            if lib.rq_enc_i8(_ptr(seg), nq, self.block, _ptr(scales),
                             _ptr(q)):
                return None   # inf/nan in a block: whole segment exact
        else:
            body = seg[:nq].reshape(nb, self.block)
            absmax = np.abs(body).max(axis=1)
            if not np.isfinite(absmax).all():
                return None
            np.divide(absmax, 127.0, out=scales)
            scales[absmax < _I8_TINY] = 0.0
            inv = np.zeros_like(scales)
            np.divide(np.float32(1.0), scales, out=inv, where=scales > 0)
            f = self._scratch("f32", nq, np.float32).reshape(nb, self.block)
            np.multiply(body, inv[:, None], out=f)
            np.rint(f, out=f)
            np.copyto(q.reshape(nb, self.block), f, casting="unsafe")
        # the sub-block tail rides exact float32 (block-scale layout
        # only covers whole blocks; the copy pins it so the scratch
        # tuple never aliases caller memory)
        tail = seg[nq:].copy()
        return (_MAGIC, WIRE_INT8, n, self.block, scales, q, tail)

    # ------------------------------------------------------------ decode

    def _dec(self, val, out: np.ndarray):
        """Dequantize wire tuple `val` into float32 array `out`."""
        lib = _native()
        if val[1] == WIRE_BF16:
            q = np.ascontiguousarray(val[3], dtype=np.uint16)
            if lib is not None:
                lib.rq_dec_bf16(_ptr(q), q.size, _ptr(out))
            else:
                np.left_shift(q.astype(np.uint32), 16,
                              out=out.view(np.uint32))
            return
        _, _, n, block, scales, q, tail = val
        scales = np.ascontiguousarray(scales, dtype=np.float32)
        q = np.ascontiguousarray(q, dtype=np.int8)
        nq = q.size
        if lib is not None:
            lib.rq_dec_i8(_ptr(q), _ptr(scales), block, _ptr(out), nq)
        else:
            nb = nq // block
            np.multiply(q.reshape(nb, block), scales[:, None],
                        out=out[:nq].reshape(nb, block))
        if n > nq:
            np.copyto(out[nq:], tail)

    def decode(self, val, out: np.ndarray | None = None) -> np.ndarray:
        """Dequantized float32 array for wire tuple `val` — into `out`
        when given, else into a reusable scratch buffer (valid until the
        next decode of the same size)."""
        n = val[2]
        if out is None:
            out = self._dec_scratch.get(n)
            if out is None:
                out = self._dec_scratch[n] = np.empty(n, np.float32)
        self._dec(val, out)
        return out

    def maybe_decode(self, val, out: np.ndarray | None = None):
        """decode() for wire tuples; pass anything else through (a peer
        may have fallen back to exact for this segment)."""
        if is_wire(val):
            return self.decode(val, out)
        if out is not None:
            np.copyto(out, val)
            return out
        return val

    def copy_into(self, val, out: np.ndarray):
        """out[:] = value of `val` (wire tuple or plain array) — the
        ring's allgather-phase write."""
        if is_wire(val):
            self._dec(val, out)
        else:
            np.copyto(out, val)

    def reduce_into(self, src: np.ndarray, val, acc: np.ndarray):
        """acc = src + value of `val` — the ring's reduce step, fused
        with the dequantize when the native kernels are present (one
        pass instead of decode-then-add). Only ``sum`` groups are
        eligible for quantization, so the op is fixed."""
        if not is_wire(val):
            np.add(src, val, out=acc)
            return
        lib = _native()
        if lib is None:
            np.add(src, self.decode(val), out=acc)
            return
        if val[1] == WIRE_BF16:
            q = np.ascontiguousarray(val[3], dtype=np.uint16)
            lib.rq_dec_add_bf16(_ptr(q), _ptr(src), _ptr(acc), q.size)
            return
        _, _, n, block, scales, q, tail = val
        scales = np.ascontiguousarray(scales, dtype=np.float32)
        q = np.ascontiguousarray(q, dtype=np.int8)
        nq = q.size
        lib.rq_dec_add_i8(_ptr(q), _ptr(scales), block, _ptr(src),
                          _ptr(acc), nq)
        if n > nq:
            np.add(src[nq:], tail, out=acc[nq:])

    def add_both(self, val_a, val_b, acc: np.ndarray):
        """acc = deq(val_a) + deq(val_b), both wire tuples of the SAME
        format and length — one fused pass. This is the pairwise
        exchange's reduce: both contributions ride the wire quantized,
        so every rank adds identical decoded values (and float add is
        commutative bit-for-bit on finite values, so operand order
        doesn't break the rank-identical-results property)."""
        if val_a[1] != val_b[1] or val_a[2] != val_b[2] or \
                (val_a[1] == WIRE_INT8 and val_a[3] != val_b[3]):
            # mismatched peer framing (e.g. ranks configured different
            # block sizes): decode-then-add, slow but safe
            self._dec(val_a, acc)
            np.add(acc, self.decode(val_b), out=acc)
            return
        lib = _native()
        if lib is None:
            # two decodes + one add; the second decode uses the shared
            # size-keyed scratch, so decode A straight into acc first
            self._dec(val_a, acc)
            np.add(acc, self.decode(val_b), out=acc)
            return
        if val_a[1] == WIRE_BF16:
            qa = np.ascontiguousarray(val_a[3], dtype=np.uint16)
            qb = np.ascontiguousarray(val_b[3], dtype=np.uint16)
            lib.rq_add_qq_bf16(_ptr(qa), _ptr(qb), _ptr(acc), qa.size)
            return
        _, _, n, block, sa, qa, ta = val_a
        _, _, _n2, _b2, sb, qb, tb = val_b
        qa = np.ascontiguousarray(qa, dtype=np.int8)
        qb = np.ascontiguousarray(qb, dtype=np.int8)
        sa = np.ascontiguousarray(sa, dtype=np.float32)
        sb = np.ascontiguousarray(sb, dtype=np.float32)
        nq = qa.size
        lib.rq_add_qq_i8(_ptr(qa), _ptr(sa), _ptr(qb), _ptr(sb), block,
                         _ptr(acc), nq)
        if n > nq:
            np.add(ta, tb, out=acc[nq:])

    # --------------------------------------------------------- telemetry

    def sample_error(self, seg: np.ndarray, enc: tuple,
                     max_elems: int = 16384) -> float:
        """Measured max-abs quantization error of (a prefix of) one
        just-encoded segment, normalized by the prefix's absmax — the
        scale-free number the quant-error histogram records. Sampled
        (one segment per op, bounded prefix) so telemetry never doubles
        the encode cost."""
        n = min(int(seg.size), max_elems)
        if self.tag == WIRE_INT8:
            n = min(n, int(enc[5].size))   # stay inside quantized blocks
        if n == 0:
            return 0.0
        trimmed = _trim(enc, n)
        n = trimmed[2]                     # _trim may round up to a block
        ref = seg[:n]
        deq = self.decode(trimmed, out=None)
        denom = float(np.abs(ref).max())
        if denom == 0.0 or not np.isfinite(denom):
            return 0.0
        return float(np.abs(deq[:n] - ref).max()) / denom


def wrap_p2p(enc: tuple, shape) -> tuple:
    """Wrap one encoded wire tuple as a shape-carrying p2p payload."""
    return (P2P_MAGIC, tuple(int(d) for d in shape), enc)


def is_p2p_wire(val) -> bool:
    return isinstance(val, tuple) and len(val) == 3 and val[0] == P2P_MAGIC


_p2p_decoder: WireCodec | None = None


def maybe_decode_p2p(val):
    """Decode a p2p-wrapped wire payload back to a float32 array of the
    original shape; anything else passes through unchanged. Allocates a
    fresh owned array per call (p2p results escape to callers — codec
    scratch reuse would alias successive receives)."""
    global _p2p_decoder
    if not is_p2p_wire(val):
        return val
    _, shape, enc = val
    if _p2p_decoder is None:
        # decode is format-driven by the tuple's own tag/block — the
        # codec's configured format only governs ENCODE, so one shared
        # instance serves both bf16 and int8 payloads
        _p2p_decoder = WireCodec("bf16", 1024)
    out = np.empty(int(enc[2]), np.float32)
    _p2p_decoder._dec(enc, out)
    return out.reshape(shape)


def _trim(enc: tuple, n: int) -> tuple:
    """A view of wire tuple `enc` covering only its first `n` elements
    (n must stay within the quantized body for int8)."""
    if enc[1] == WIRE_BF16:
        return (_MAGIC, WIRE_BF16, n, enc[3][:n])
    _, _, _total, block, scales, q, _tail = enc
    nb = max(1, n // block)
    n = nb * block
    return (_MAGIC, WIRE_INT8, n, block, scales[:nb], q[:n],
            np.empty(0, np.float32))
