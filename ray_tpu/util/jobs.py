"""Named jobs — the multi-tenant face of the scheduling plane.

Reference tier: the Ray paper's GCS/distributed-scheduler arbitration
(arXiv:1712.05889 §4) — competing workloads share one cluster through
per-job resource QUOTAS and a PRIORITY class. A job here is a named
policy record in the GCS (``_private/gcs.py`` job table), attached to
work as a LABEL: placement groups carry it explicitly
(``placement_group(..., job=...)``, ``ScalingConfig(job=...)``) and
plain task/actor leases inherit this process's *current job*
(``set_current_job``).

Semantics:

- **Quota** (``{"CPU": 8, "TPU": 4}``): a cap on the job's concurrent
  cluster-wide usage (CREATED placement-group bundles plus granted
  leases). Enforcement is all-or-nothing at placement-group admission —
  the gang that would exceed the quota stays PENDING whole, never
  partially placed — and by throttling lease grants at the raylets
  while the job is over. A quota RAISED at runtime unblocks queued
  gangs immediately.
- **Priority** (int, higher wins): pending bundles are scheduled
  highest-priority-first (fair-share by dominant resource within a
  priority class), and a higher-priority gang that cannot place
  PREEMPTS the lowest-priority job's newest gang — warning + grace
  window (``gcs_preempt_grace_s``) so the victim checkpoints, then its
  bundles are reclaimed and it re-queues to resume when capacity
  returns.
"""
from __future__ import annotations

import threading

_lock = threading.Lock()
_current_job: str | None = None


def set_current_job(name: str | None):
    """Label this process's subsequent work (task/actor leases, and
    placement groups created without an explicit ``job=``) as belonging
    to ``name``. ``None`` clears the label. Process-global: a driver
    hosting several tenants should pass ``job=`` explicitly instead."""
    global _current_job
    with _lock:
        _current_job = name


def current_job() -> str | None:
    return _current_job


def _gcs_call(method: str, **kw):
    from ray_tpu._private import api

    worker = api._require_worker()
    return worker.gcs.call(method, **kw)


def register_job(name: str, quota: dict | None = None,
                 priority: int | None = None) -> dict:
    """Create-or-update a named job (idempotent). ``None`` keeps the
    existing quota/priority (priority defaults to 0 on first create) —
    bumping a quota never silently demotes the job's priority. Returns
    the job's snapshot (policy + live usage/share/PG rollup)."""
    return _gcs_call("register_job", name=name, quota=quota,
                     priority=priority)


def update_job(name: str, quota: dict | None = None,
               priority: int | None = None) -> dict:
    """Change a registered job's quota and/or priority at runtime.
    Raising a quota re-drives the pending queue on the spot."""
    return _gcs_call("update_job", name=name, quota=quota,
                     priority=priority)


def remove_job(name: str) -> bool:
    return _gcs_call("remove_job", name=name)


def get_job(name: str) -> dict | None:
    return _gcs_call("get_job", name=name)


def list_jobs() -> list[dict]:
    """Every job's policy + live usage (includes label-only jobs that
    were never registered, with default policy)."""
    return _gcs_call("list_jobs")


def preempt_job(name: str, grace_s: float | None = None,
                pg_name: str | None = None) -> str | None:
    """Force-preempt the named job's newest running gang (admin escape
    hatch; also what the fault DSL's ``preempt_job`` primitive drives).
    ``pg_name`` narrows the victim to the job's gang of that name —
    how the Serve controller drains ONE replica's capacity through the
    warning machinery instead of whichever gang is newest. Returns the
    victim placement group id hex, or None."""
    return _gcs_call("preempt_job", name=name, grace_s=grace_s,
                     pg_name=pg_name)
