"""ActorPool (reference: python/ray/util/actor_pool.py) — work distribution
over a fixed set of actors with streaming results."""
from __future__ import annotations

import ray_tpu


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._pending: list = []          # (fn, value) waiting for an actor
        self._results_order: list = []    # refs in submit order
        self._next_return = 0

    def submit(self, fn, value):
        """fn: (actor, value) -> ObjectRef"""
        if self._idle:
            actor = self._idle.pop()
            ref = fn(actor, value)
            self._future_to_actor[ref] = actor
            self._results_order.append(ref)
        else:
            self._pending.append((fn, value))

    def _reclaim(self, ref):
        actor = self._future_to_actor.pop(ref, None)
        if actor is not None:
            if self._pending:
                fn, value = self._pending.pop(0)
                new_ref = fn(actor, value)
                self._future_to_actor[new_ref] = actor
                self._results_order.append(new_ref)
            else:
                self._idle.append(actor)

    def get_next(self, timeout: float | None = None):
        """Next result in submission order."""
        if self._next_return >= len(self._results_order):
            # invariant: each consumed ref reclaims its actor and drains one
            # pending item into _results_order, so an index beyond the list
            # means nothing was submitted
            raise StopIteration("no pending results")
        ref = self._results_order[self._next_return]
        value = ray_tpu.get(ref, timeout=timeout)   # may raise: cursor stays
        self._next_return += 1
        self._reclaim(ref)
        return value

    def get_next_unordered(self, timeout: float | None = None):
        outstanding = [r for r in self._results_order[self._next_return:]
                       if r in self._future_to_actor]
        if not outstanding:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(outstanding, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        ref = ready[0]
        self._results_order.remove(ref)
        self._results_order.insert(self._next_return, ref)
        self._next_return += 1
        value = ray_tpu.get(ref)
        self._reclaim(ref)
        return value

    def map(self, fn, values: list):
        for v in values:
            self.submit(fn, v)
        for _ in values:
            yield self.get_next()

    def map_unordered(self, fn, values: list):
        for v in values:
            self.submit(fn, v)
        for _ in values:
            yield self.get_next_unordered()

    def has_next(self) -> bool:
        return self._next_return < len(self._results_order) \
            or bool(self._pending)

    def has_free(self) -> bool:
        return bool(self._idle)
