"""Distributed FIFO queue backed by an actor
(reference: python/ray/util/queue.py)."""
from __future__ import annotations

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        import queue as _q

        self.q = _q.Queue(maxsize=maxsize)

    def put(self, item, block=True, timeout=None):
        import queue as _q

        try:
            self.q.put(item, block=block, timeout=timeout)
            return True
        except _q.Full:
            return False

    def get(self, block=True, timeout=None):
        import queue as _q

        try:
            return (True, self.q.get(block=block, timeout=timeout))
        except _q.Empty:
            return (False, None)

    def qsize(self):
        return self.q.qsize()

    def empty(self):
        return self.q.empty()

    def full(self):
        return self.q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: dict | None = None):
        opts = {"num_cpus": 0, "max_concurrency": 8,
                **(actor_options or {})}
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(
            maxsize)

    def put(self, item, block: bool = True, timeout: float | None = None):
        ok = ray_tpu.get(self.actor.put.remote(item, block, timeout))
        if not ok:
            raise Full("queue full")

    def get(self, block: bool = True, timeout: float | None = None):
        ok, item = ray_tpu.get(self.actor.get.remote(block, timeout))
        if not ok:
            raise Empty("queue empty")
        return item

    def put_nowait(self, item):
        self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)
