"""Pool — the multiprocessing.Pool surface on actors.

Contract-faithful to the stdlib subset it mimics: map/imap pass each
iterable item as ONE argument (tuples included); starmap splats. Work is
dispatched pull-based — each worker holds at most one chunk in flight and
idle workers pick up the next chunk as soon as they finish (the stdlib's
shared-queue behavior; static round-robin would stall a pool behind one
slow item).
"""
from __future__ import annotations

import threading


class _PoolWorker:
    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_batch(self, fn, chunk, star):
        if star:
            return [fn(*item) for item in chunk]
        return [fn(item) for item in chunk]


class AsyncResult:
    def __init__(self):
        self._event = threading.Event()
        self._value = None
        self._error = None

    def _set(self, value=None, error=None):
        self._value = value
        self._error = error
        self._event.set()

    def get(self, timeout: float | None = None):
        if not self._event.wait(timeout):
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value

    def wait(self, timeout: float | None = None):
        self._event.wait(timeout)

    def ready(self) -> bool:
        return self._event.is_set()

    def successful(self) -> bool:
        if not self._event.is_set():
            raise ValueError("result not ready")
        return self._error is None


class Pool:
    """Drop-in for multiprocessing.Pool (the commonly used subset):
    map / map_async / starmap / imap / imap_unordered / apply /
    apply_async / close / join / terminate; context-manager capable."""

    def __init__(self, processes: int | None = None,
                 initializer=None, initargs: tuple = ()):
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._ray = ray_tpu
        n = processes or int(ray_tpu.cluster_resources().get("CPU", 2))
        n = max(1, min(n, 64))
        # one CPU per worker, like the reference shim: the pool's size then
        # actually bounds and spreads CPU use across the cluster
        worker_cls = ray_tpu.remote(_PoolWorker)
        self._workers = [
            worker_cls.options(num_cpus=1).remote(initializer, initargs)
            for _ in range(n)
        ]
        self._closed = False

    # ------------------------------------------------------------- dispatch
    def _chunks(self, items, chunksize):
        if chunksize is None:
            chunksize = max(1, len(items) // (len(self._workers) * 4) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)]

    def _dispatch(self, fn, chunks, star):
        """Pull-based scheduling generator: yields (chunk_index, values) as
        chunks complete; at most one chunk in flight per worker."""
        ray = self._ray
        free = list(self._workers)
        inflight: dict = {}
        next_chunk = 0
        while next_chunk < len(chunks) or inflight:
            while free and next_chunk < len(chunks):
                w = free.pop()
                ref = w.run_batch.remote(fn, chunks[next_chunk], star)
                inflight[ref] = (next_chunk, w)
                next_chunk += 1
            done, _ = ray.wait(list(inflight), num_returns=1, timeout=300)
            if not done:
                raise TimeoutError("pool chunk made no progress in 300s")
            for ref in done:
                idx, w = inflight.pop(ref)
                free.append(w)
                yield idx, ray.get(ref)

    def _map_all(self, fn, iterable, chunksize, star):
        self._check()
        items = list(iterable)
        chunks = self._chunks(items, chunksize)
        results: list = [None] * len(chunks)
        for idx, values in self._dispatch(fn, chunks, star):
            results[idx] = values
        return [v for chunk in results for v in chunk]

    # ------------------------------------------------------------------ api
    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def apply(self, fn, args: tuple = (), kwds: dict | None = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args: tuple = (), kwds: dict | None = None):
        self._check()
        result = AsyncResult()
        kwds = dict(kwds or {})
        call_args = tuple(args)

        def run():
            try:
                out = self._map_all(
                    lambda packed: fn(*packed[0], **packed[1]),
                    [(call_args, kwds)], 1, star=False)
                result._set(value=out[0])
            except BaseException as e:  # noqa: BLE001
                result._set(error=e)

        threading.Thread(target=run, daemon=True).start()
        return result

    def map(self, fn, iterable, chunksize: int | None = None):
        return self._map_all(fn, iterable, chunksize, star=False)

    def starmap(self, fn, iterable, chunksize: int | None = None):
        return self._map_all(fn, iterable, chunksize, star=True)

    def map_async(self, fn, iterable, chunksize: int | None = None):
        self._check()
        result = AsyncResult()

        def run():
            try:
                result._set(value=self._map_all(fn, iterable, chunksize,
                                                star=False))
            except BaseException as e:  # noqa: BLE001
                result._set(error=e)

        threading.Thread(target=run, daemon=True).start()
        return result

    def imap(self, fn, iterable, chunksize: int | None = None):
        self._check()
        items = list(iterable)
        chunks = self._chunks(items, chunksize or 1)
        buffered: dict = {}
        emit = 0
        for idx, values in self._dispatch(fn, chunks, star=False):
            buffered[idx] = values
            while emit in buffered:
                yield from buffered.pop(emit)
                emit += 1

    def imap_unordered(self, fn, iterable, chunksize: int | None = None):
        self._check()
        items = list(iterable)
        chunks = self._chunks(items, chunksize or 1)
        for _idx, values in self._dispatch(fn, chunks, star=False):
            yield from values

    # ------------------------------------------------------------ lifecycle
    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for w in self._workers:
            try:
                self._ray.kill(w)
            except Exception:
                pass
        self._workers = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.terminate()
        return False
