"""multiprocessing.Pool API over runtime actors.

Reference: python/ray/util/multiprocessing/ (Pool shim) — lets
`multiprocessing.Pool` code scale past one machine by swapping the import.
Pool methods map onto an actor pool; imap/imap_unordered stream results as
they complete.
"""
from ray_tpu.util.multiprocessing.pool import Pool

__all__ = ["Pool"]
