"""Accelerator-type constants + helpers (reference:
python/ray/util/accelerators/ — NVIDIA_TESLA_* constants used in
`@ray.remote(accelerator_type=...)`; here the first-class citizens are
TPU generations, and the helpers read the TPU VM runtime env the way
the reference's TPU pod detection does)."""
from __future__ import annotations

import os

# accelerator_type constants (GKE/GCE TPU accelerator type strings)
TPU_V2 = "TPU-V2"
TPU_V3 = "TPU-V3"
TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5LITEPOD"
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"

_GENERATION_PREFIXES = {
    "v2": TPU_V2, "v3": TPU_V3, "v4": TPU_V4,
    "v5litepod": TPU_V5E, "v5e": TPU_V5E, "v5p": TPU_V5P,
    "v6e": TPU_V6E,
}


def get_current_pod_name() -> str | None:
    """The TPU pod/slice this host belongs to (TPU_NAME on TPU VMs)."""
    return os.environ.get("TPU_NAME") or os.environ.get("TPU_SLICE_ID")


def get_current_pod_worker_count() -> int | None:
    """Number of hosts in this pod (TPU_WORKER_HOSTNAMES on TPU VMs)."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES")
    if hosts:
        return len(hosts.split(","))
    return None


def get_current_accelerator_type() -> str | None:
    """Normalized accelerator type of this host (e.g. 'TPU-V5LITEPOD'
    for a v5litepod-16 slice), or None off-TPU."""
    raw = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    if not raw:
        return None
    gen = raw.split("-")[0].lower()
    return _GENERATION_PREFIXES.get(gen, f"TPU-{gen.upper()}")


def get_current_topology() -> str | None:
    """Chip topology string of this slice (e.g. '2x4'), or None."""
    topo = os.environ.get("TPU_TOPOLOGY")
    if topo:
        return topo
    raw = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    # v5litepod-16 → 16 chips; topology proper only comes from
    # TPU_TOPOLOGY, so expose the chip count form when that's all we have
    if "-" in raw:
        return raw.split("-", 1)[1]
    return None
