"""Client-mode driver — connects to a ClientServer over one socket.

Reference: python/ray/util/client/ (RayAPIStub / ClientContext,
architecture in util/client/ARCHITECTURE.md). The context duck-types the
CoreWorker surface the public API layer uses (put/get/wait,
register_function, submit_task, create_actor, submit_actor_task,
cancel_task, `.gcs.call`), so once it is installed via
``set_current_worker`` every ``ray_tpu.*`` call transparently routes
through the proxy — the client process needs reachability to exactly one
host:port.
"""
from __future__ import annotations

import pickle
import threading

from ray_tpu._private import serialization as ser
from ray_tpu._private.object_ref import ObjectRef, ReferenceCounter
from ray_tpu._private.protocol import ReconnectingRpcClient


def _poll_slice() -> float:
    from ray_tpu._private.config import get_config

    return get_config("client_poll_slice_s")


class _GcsProxy:
    """`.call()`-compatible stand-in for the worker's GCS client; forwards
    through the client channel so API helpers (nodes, get_actor, kill)
    work unchanged in client mode."""

    def __init__(self, ctx: "ClientContext"):
        self._ctx = ctx
        self.addr = ctx.server_addr

    def call(self, method: str, **kw):
        return self._ctx._rpc.call("client_gcs_call", gcs_method=method,
                                   kw=kw)



class ClientContext:
    """The client-mode 'worker'. Created by
    ``ray_tpu.init(address="ray://host:port")``."""

    mode = "client"

    def __init__(self, host: str, port: int):
        import uuid as _uuid

        self.server_addr = (host, port)
        # session id survives reconnects: the server keeps pinned refs,
        # in-flight chunk state, and the submit dedup cache alive for a
        # grace window, so a dropped socket resumes instead of losing
        # every outstanding ref (reference: client session resume)
        self.session_id = f"cs-{_uuid.uuid4().hex}"
        self._rpc = ReconnectingRpcClient(
            (host, port),
            on_reconnect=lambda raw: raw.call(
                "client_hello", session_id=self.session_id))
        hello = self._rpc.call("client_hello", session_id=self.session_id)
        self._chunk_bytes = int(hello.get("chunk_bytes") or 4 * 1024 * 1024)
        import itertools as _it

        self._req_counter = _it.count(1)   # thread-safe id mint
        self.reference_counter = ReferenceCounter(on_zero=self._release)
        self.gcs = _GcsProxy(self)
        self._func_cache: dict = {}
        self._closed = False
        # identity attrs the RayContext/RuntimeContext helpers read
        import uuid

        self.node_id = f"client-{uuid.uuid4().hex[:8]}"
        self.worker_id = self.node_id
        self.job_id = 0
        self.actor_id = None
        self._actor_spec = None

    # ------------------------------------------------------------- plumbing
    def _release(self, object_id: bytes):
        # fire-and-forget: this runs from ObjectRef.__del__ — a blocking
        # round trip here would stall whatever thread GC happens on
        if self._closed:
            return
        try:
            self._rpc.push("client_release", ids=[object_id])
        except Exception:
            pass

    def _dumps_args(self, args, kwargs) -> bytes:
        # cloudpickle, matching direct mode's ser.serialize: lambdas,
        # closures, and interactively-defined classes must survive transport
        import cloudpickle

        return cloudpickle.dumps((args, kwargs))

    def _next_req_id(self) -> str:
        return f"{self.session_id}:{next(self._req_counter)}"

    # ------------------------------------------------------------ object api
    def put(self, value) -> ObjectRef:
        import uuid as _uuid

        import cloudpickle

        blob = cloudpickle.dumps(value)
        if len(blob) <= self._chunk_bytes:
            ref_id, owner = self._rpc.call(
                "client_put", blob=blob, req_id=self._next_req_id())
        else:
            # stream bounded chunks so this put can't head-of-line-block
            # the shared socket with one giant frame; chunks carry their
            # index (a reconnect replay overwrites, never duplicates) and
            # the commit carries a req_id (a replayed commit returns the
            # first put's ref instead of consuming an empty upload)
            upload_id = f"u-{_uuid.uuid4().hex}"
            view = memoryview(blob)
            for i, off in enumerate(range(0, len(blob),
                                          self._chunk_bytes)):
                self._rpc.call("client_put_chunk", upload_id=upload_id,
                               index=i,
                               blob_part=bytes(
                                   view[off:off + self._chunk_bytes]))
            ref_id, owner = self._rpc.call("client_put",
                                           upload_id=upload_id,
                                           req_id=self._next_req_id())
        return ObjectRef(ref_id, owner, worker=self)

    def get(self, refs, timeout=None):
        from ray_tpu.exceptions import GetTimeoutError

        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        ids = [r.id for r in ref_list]
        # timeout=None re-polls in bounded slices forever — direct mode
        # blocks indefinitely too. Each slice issues ONE RPC; if the reply
        # (possibly a huge pickle) outlives the wait window, keep waiting
        # on the SAME in-flight future with a growing window rather than
        # reissuing the op — a reissue would queue another full-size reply
        # behind the first on the same socket (advisor + review, round 4).
        while True:
            slice_t = timeout if timeout is not None else _poll_slice()
            fut = self._rpc.call_async("client_get", ids=ids,
                                       op_timeout=slice_t)
            wait = slice_t + 30.0
            try:
                while True:
                    try:
                        blob = fut.result(wait)
                        break
                    except GetTimeoutError:
                        # server-side: object not ready within op_timeout
                        raise
                    except TimeoutError:
                        # RPC-layer: reply still in transit
                        if timeout is not None:
                            raise
                        wait = min(wait * 2, 3600.0)
                break
            except GetTimeoutError:
                if timeout is not None:
                    raise
        reply = blob
        if isinstance(reply, dict) and "chunked" in reply:
            # large value: pull bounded chunks (the server parked the
            # serialized reply in the session). The caller's deadline
            # bounds every chunk pull; without one, 120s per chunk.
            import time as _time

            deadline = (None if timeout is None
                        else _time.time() + timeout)
            get_id, n = reply["chunked"], reply["n_chunks"]
            pieces = []
            for i in range(n):
                per_chunk = 120.0 if deadline is None else max(
                    0.001, deadline - _time.time())
                pieces.append(self._rpc.call(
                    "client_get_chunk", get_id=get_id, index=i,
                    last=(i == n - 1), timeout=per_chunk))
            blob = b"".join(pieces)
        elif isinstance(reply, dict):
            blob = reply["blob"]
        values = pickle.loads(blob)
        return values[0] if single else values

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        by_id = {r.id: r for r in refs}
        ids = [r.id for r in refs]
        while True:
            slice_t = timeout if timeout is not None else _poll_slice()
            ready_ids, rest_ids = self._rpc.call(
                "client_wait", ids=ids, num_returns=num_returns,
                op_timeout=slice_t, fetch_local=fetch_local,
                timeout=slice_t + 30)
            if timeout is not None or len(ready_ids) >= num_returns:
                return ([by_id[i] for i in ready_ids],
                        [by_id[i] for i in rest_ids])

    # -------------------------------------------------------------- task api
    def register_function(self, fn) -> bytes:
        import hashlib

        blob = ser.dumps_function(fn)
        func_hash = hashlib.sha1(blob).digest()  # content-addressed, like
        if func_hash not in self._func_cache:    # CoreWorker.register_function
            self._rpc.call("client_register_function", blob=blob)
            self._func_cache[func_hash] = True
        return func_hash

    def submit_task(self, func_hash: bytes, args, kwargs, **options):
        pairs = self._rpc.call(
            "client_submit_task", func_hash=func_hash,
            payload=self._dumps_args(args, kwargs), options=options,
            req_id=self._next_req_id())
        return [ObjectRef(i, owner, worker=self) for i, owner in pairs]

    def create_actor(self, class_hash: bytes, args, kwargs, *, options):
        return self._rpc.call(
            "client_create_actor", class_hash=class_hash,
            payload=self._dumps_args(args, kwargs), options=options,
            req_id=self._next_req_id())

    def submit_actor_task(self, actor_id: bytes, method_name: str, args,
                          kwargs, **options):
        pairs = self._rpc.call(
            "client_submit_actor_task", actor_id=actor_id,
            method_name=method_name,
            payload=self._dumps_args(args, kwargs), options=options,
            req_id=self._next_req_id())
        return [ObjectRef(i, owner, worker=self) for i, owner in pairs]

    def cancel_task(self, ref: ObjectRef, force: bool = False):
        self._rpc.call("client_cancel", ref_id=ref.id, force=force)

    def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        """Server-side kill — the client can't dial raylets directly."""
        self._rpc.call("client_kill", actor_id=actor_id,
                       no_restart=no_restart)

    def available_resources(self) -> dict:
        """Server-side aggregation — raylet addresses are cluster-internal."""
        return self._rpc.call("client_available_resources")

    # ------------------------------------------------------------------ misc
    def as_future(self, ref: ObjectRef):
        import concurrent.futures

        fut: concurrent.futures.Future = concurrent.futures.Future()

        def _wait():
            try:
                fut.set_result(self.get(ref))
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=_wait, daemon=True).start()
        return fut

    def shutdown(self):
        self._closed = True
        self.reference_counter.shutdown()   # stop the drainer thread
        try:
            self._rpc.close()
        except Exception:
            pass


def connect(address: str) -> ClientContext:
    """address is "host:port" (without the ray:// scheme)."""
    host, port = address.rsplit(":", 1)
    return ClientContext(host, int(port))
