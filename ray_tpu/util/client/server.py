"""Client-mode proxy server — the `ray://` endpoint.

Reference: python/ray/util/client/server/server.py:96 (RayletServicer) —
a gRPC proxy that lets an out-of-cluster process drive the cluster through
ONE endpoint instead of dialing GCS/raylets/peers directly. This server
runs inside a process that is already a driver (``ray_tpu.init()`` done);
every client op is executed against the local CoreWorker.

Sessions, not connections (reference: the client's session-resume +
reconnect grace): state is keyed by a client-generated session id the
client presents in ``client_hello``. Pinned refs, chunk uploads, and
the submit dedup cache survive a dropped socket for
``client_session_ttl_s``; a reconnecting client resumes exactly where
it was. Large values move in bounded chunks (``client_chunk_bytes``)
so one giant get/put frame can't head-of-line-block the shared socket.
Submit ops carry a client request id; replaying one (the client retried
across a reconnect) returns the cached result instead of double-
submitting (reference: client req-id dedup on the data channel).
"""
from __future__ import annotations

import hashlib
import pickle
import threading
import time

from ray_tpu._private.protocol import RpcServer


def _ttl() -> float:
    from ray_tpu._private.config import get_config

    return float(get_config("client_session_ttl_s"))


def _chunk_bytes() -> int:
    from ray_tpu._private.config import get_config

    return int(get_config("client_chunk_bytes"))


class _Session:
    __slots__ = ("pinned", "uploads", "downloads", "dedup",
                 "disconnected_at", "current_conn")

    def __init__(self):
        self.pinned: dict[bytes, object] = {}   # ref_id -> ObjectRef
        # upload_id -> (created_at, {index: chunk}) — keyed by index so
        # a retried chunk (reconnect replay) overwrites, not duplicates
        self.uploads: dict[str, tuple] = {}
        # get_id -> (created_at, blob) — reclaimed by AGE, never on the
        # last fetch (a retried last-chunk pull must still succeed)
        self.downloads: dict[str, tuple] = {}
        self.dedup: dict[str, object] = {}      # req_id -> cached reply
        self.disconnected_at: float | None = None
        self.current_conn: str | None = None    # latest bound conn.id


class _ClientHandler:
    def __init__(self):
        self._lock = threading.Lock()
        self._sessions: dict[str, _Session] = {}
        self._conn_session: dict[str, str] = {}   # conn.id -> session_id
        self._stop = threading.Event()
        self._sweeper = threading.Thread(target=self._sweep, daemon=True,
                                         name="client-session-sweeper")
        self._sweeper.start()

    def shutdown(self):
        self._stop.set()

    # ------------------------------------------------------------ lifecycle
    def on_connect(self, conn):
        pass   # state binds at client_hello, not connect

    def on_disconnect(self, conn):
        with self._lock:
            sid = self._conn_session.pop(conn.id, None)
            if sid is not None:
                session = self._sessions.get(sid)
                # only the session's CURRENT connection starts the grace
                # clock — the late EOF of a half-open predecessor must
                # not condemn a session a newer connection is using
                if session is not None and \
                        session.current_conn == conn.id:
                    session.disconnected_at = time.time()

    def _sweep(self):
        while not self._stop.wait(5.0):
            cutoff = time.time() - _ttl()
            with self._lock:
                for sid in [s for s, ses in self._sessions.items()
                            if ses.disconnected_at is not None
                            and ses.disconnected_at < cutoff]:
                    del self._sessions[sid]
                # abandoned transfers leak whole serialized values if
                # only session expiry reclaims them (a live session can
                # abort a chunked get forever) — age them out too
                for ses in self._sessions.values():
                    for table in (ses.uploads, ses.downloads):
                        for key in [k for k, (ts, _v) in table.items()
                                    if ts < cutoff]:
                            del table[key]

    def rpc_client_hello(self, conn, session_id: str):
        """Bind this connection to a (new or resumed) session."""
        with self._lock:
            session = self._sessions.get(session_id)
            resumed = session is not None
            if session is None:
                session = self._sessions[session_id] = _Session()
            session.disconnected_at = None
            session.current_conn = conn.id
            self._conn_session[conn.id] = session_id
        return {"resumed": resumed, "chunk_bytes": _chunk_bytes()}

    def _session(self, conn) -> _Session:
        with self._lock:
            sid = self._conn_session.get(conn.id)
            session = self._sessions.get(sid) if sid else None
        if session is None:
            raise RuntimeError("client connection has no session "
                               "(client_hello missing)")
        return session

    def _pin(self, conn, refs):
        session = self._session(conn)
        with self._lock:
            for r in refs:
                session.pinned[r.id] = r

    def _worker(self):
        from ray_tpu._private.worker_runtime import current_worker

        worker = current_worker()
        if worker is None:
            raise RuntimeError("client server host process lost its driver")
        return worker

    def _deduped(self, conn, req_id, fn):
        """Submit-op dedup: a retried request (client reconnected before
        the reply landed) returns the FIRST submission's result. An
        in-flight marker parks a replay that arrives WHILE the first is
        still executing — without it the check-then-act window would
        run fn() twice, the exact double-submit this exists to stop."""
        session = self._session(conn)
        if not req_id:
            return fn()
        while True:
            with self._lock:
                entry = session.dedup.get(req_id)
                if entry is None:
                    event = threading.Event()
                    session.dedup[req_id] = ("pending", event)
                    break
                state, value = entry
                if state == "done":
                    return value
            # a first submission is mid-flight: wait for its outcome
            value.wait(timeout=300)
        try:
            result = fn()
        except BaseException:
            with self._lock:
                session.dedup.pop(req_id, None)   # retry may re-run
            event.set()
            raise
        with self._lock:
            session.dedup[req_id] = ("done", result)
            if len(session.dedup) > 4096:   # bound the cache
                for k in [k for k, (st, _v) in list(session.dedup.items())
                          if st == "done"][:1024]:
                    del session.dedup[k]
        event.set()
        return result

    # ------------------------------------------------------- chunked upload
    def rpc_client_put_chunk(self, conn, upload_id: str, blob_part: bytes,
                             index: int = 0):
        session = self._session(conn)
        with self._lock:
            entry = session.uploads.get(upload_id)
            if entry is None:
                entry = (time.time(), {})
            # refresh the age stamp on EVERY chunk: a slow multi-minute
            # transfer must not be swept mid-flight
            session.uploads[upload_id] = (time.time(), entry[1])
            entry[1][index] = blob_part   # replay overwrites, no dup
        return True

    def rpc_client_put(self, conn, blob: bytes = None,
                       upload_id: str = None, req_id: str = None):
        session = self._session(conn)

        def run():
            payload = blob
            if upload_id is not None:
                with self._lock:
                    _ts, chunks = session.uploads.pop(
                        upload_id, (0, {}))
                payload = b"".join(chunks[i]
                                   for i in sorted(chunks))
            ref = self._worker().put(pickle.loads(payload))
            self._pin(conn, [ref])
            return ref.id, ref.owner_addr

        return self._deduped(conn, req_id, run)

    # ----------------------------------------------------- chunked download
    def rpc_client_get(self, conn, ids: list, op_timeout):
        from ray_tpu._private.object_ref import ObjectRef

        import cloudpickle

        worker = self._worker()
        refs = [ObjectRef(i, worker=worker) for i in ids]
        values = worker.get(refs, timeout=op_timeout)
        blob = cloudpickle.dumps(values)
        limit = _chunk_bytes()
        if len(blob) <= limit:
            return {"blob": blob}
        # large reply: park it in the session, hand back a chunk handle —
        # the client pulls bounded pieces so this one get can't head-of-
        # line-block every other op on the shared socket
        session = self._session(conn)
        get_id = f"g{id(blob)}_{time.time_ns()}"
        with self._lock:
            session.downloads[get_id] = (time.time(), bytes(blob))
        n = (len(blob) + limit - 1) // limit
        return {"chunked": get_id, "n_chunks": n, "total": len(blob)}

    def rpc_client_get_chunk(self, conn, get_id: str, index: int,
                             last: bool = False):
        # NEVER deleted on the last fetch: a retried last-chunk pull
        # (reply lost to a reconnect) must still succeed. The age
        # sweeper reclaims the parked blob.
        session = self._session(conn)
        limit = _chunk_bytes()
        with self._lock:
            entry = session.downloads.get(get_id)
            if entry is None:
                raise RuntimeError(f"stale get handle {get_id}")
            # refresh on touch: a long pull outlives the TTL legitimately
            session.downloads[get_id] = (time.time(), entry[1])
            part = entry[1][index * limit:(index + 1) * limit]
        return part

    def rpc_client_wait(self, conn, ids: list, num_returns: int, op_timeout,
                        fetch_local: bool):
        from ray_tpu._private.object_ref import ObjectRef

        worker = self._worker()
        refs = [ObjectRef(i, worker=worker) for i in ids]
        ready, rest = worker.wait(refs, num_returns=num_returns,
                                  timeout=op_timeout,
                                  fetch_local=fetch_local)
        return [r.id for r in ready], [r.id for r in rest]

    # ------------------------------------------------------------------ ops
    def rpc_client_register_function(self, conn, blob: bytes):
        worker = self._worker()
        func_hash = hashlib.sha1(blob).digest()
        worker.gcs.call("kv_put", ns="funcs", key=func_hash, value=blob,
                        overwrite=False)
        return func_hash

    def rpc_client_submit_task(self, conn, func_hash: bytes, payload: bytes,
                               options: dict, req_id: str = None):
        def run():
            args, kwargs = pickle.loads(payload)
            refs = self._worker().submit_task(func_hash, args, kwargs,
                                              **options)
            self._pin(conn, refs)
            # id AND owner travel back: the client re-pickles refs into
            # later task args, and dependency resolution needs the owner
            return [(r.id, r.owner_addr) for r in refs]

        return self._deduped(conn, req_id, run)

    def rpc_client_create_actor(self, conn, class_hash: bytes,
                                payload: bytes, options: dict,
                                req_id: str = None):
        def run():
            args, kwargs = pickle.loads(payload)
            return self._worker().create_actor(class_hash, args, kwargs,
                                               options=options)

        return self._deduped(conn, req_id, run)

    def rpc_client_submit_actor_task(self, conn, actor_id: bytes,
                                     method_name: str, payload: bytes,
                                     options: dict, req_id: str = None):
        def run():
            args, kwargs = pickle.loads(payload)
            refs = self._worker().submit_actor_task(actor_id, method_name,
                                                    args, kwargs, **options)
            self._pin(conn, refs)
            return [(r.id, r.owner_addr) for r in refs]

        return self._deduped(conn, req_id, run)

    def rpc_client_cancel(self, conn, ref_id: bytes, force: bool):
        from ray_tpu._private.object_ref import ObjectRef

        worker = self._worker()
        worker.cancel_task(ObjectRef(ref_id, worker=worker), force=force)

    def rpc_client_gcs_call(self, conn, gcs_method: str, kw: dict):
        return self._worker().gcs.call(gcs_method, **kw)

    def rpc_client_kill(self, conn, actor_id: bytes, no_restart: bool):
        # runs the direct-dial kill from the server, which CAN reach raylets
        from ray_tpu._private.api import ActorHandle, kill

        kill(ActorHandle(actor_id), no_restart=no_restart)

    def rpc_client_available_resources(self, conn):
        from ray_tpu._private.api import available_resources

        return available_resources()

    def rpc_client_timeline(self, conn):
        from ray_tpu._private.api import timeline

        return timeline()

    def rpc_client_release(self, conn, ids: list):
        session = self._session(conn)
        with self._lock:
            for i in ids:
                session.pinned.pop(i, None)


class ClientServer:
    """Serve the `ray://` protocol from this (already-initialized) driver
    process. ``ClientServer(port).start()``; clients connect with
    ``ray_tpu.init(address="ray://host:port")``."""

    def __init__(self, port: int = 10001, host: str = "0.0.0.0"):
        self._handler = _ClientHandler()
        self._server = RpcServer(self._handler, host=host, port=port)

    @property
    def addr(self):
        return self._server.addr

    def start(self):
        self._server.start()
        return self

    def stop(self):
        self._handler.shutdown()   # the sweeper must die with the server
        self._server.stop()


_default_server: ClientServer | None = None


def serve(port: int = 10001, host: str = "0.0.0.0") -> ClientServer:
    """Start the process-wide client server (idempotent)."""
    global _default_server
    if _default_server is None:
        _default_server = ClientServer(port, host).start()
    return _default_server
