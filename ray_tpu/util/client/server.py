"""Client-mode proxy server — the `ray://` endpoint.

Reference: python/ray/util/client/server/server.py:96 (RayletServicer) —
a gRPC proxy that lets an out-of-cluster process drive the cluster through
ONE endpoint instead of dialing GCS/raylets/peers directly. This server
runs inside a process that is already a driver (``ray_tpu.init()`` done);
every client op is executed against the local CoreWorker.

Per-connection bookkeeping: every ObjectRef handed to a client is pinned
in a per-connection registry so the cluster doesn't GC it while the remote
client still holds it; the registry is dropped when the client releases
the ref (its local refcount hit zero) or disconnects (socket EOF — the
reference's client data channel tracks liveness the same way).
"""
from __future__ import annotations

import hashlib
import pickle
import threading

from ray_tpu._private.protocol import RpcServer


class _ClientHandler:
    def __init__(self):
        self._lock = threading.Lock()
        # conn.id -> {ref_id: ObjectRef}
        self._pinned: dict[str, dict] = {}

    # ------------------------------------------------------------ lifecycle
    def on_connect(self, conn):
        with self._lock:
            self._pinned[conn.id] = {}

    def on_disconnect(self, conn):
        with self._lock:
            self._pinned.pop(conn.id, None)

    def _pin(self, conn, refs):
        with self._lock:
            store = self._pinned.get(conn.id)
            if store is not None:
                for r in refs:
                    store[r.id] = r

    def _worker(self):
        from ray_tpu._private.worker_runtime import current_worker

        worker = current_worker()
        if worker is None:
            raise RuntimeError("client server host process lost its driver")
        return worker

    # ------------------------------------------------------------------ ops
    def rpc_client_put(self, conn, blob: bytes):
        ref = self._worker().put(pickle.loads(blob))
        self._pin(conn, [ref])
        return ref.id, ref.owner_addr

    def rpc_client_get(self, conn, ids: list, op_timeout):
        from ray_tpu._private.object_ref import ObjectRef

        import cloudpickle

        worker = self._worker()
        refs = [ObjectRef(i, worker=worker) for i in ids]
        values = worker.get(refs, timeout=op_timeout)
        return cloudpickle.dumps(values)

    def rpc_client_wait(self, conn, ids: list, num_returns: int, op_timeout,
                        fetch_local: bool):
        from ray_tpu._private.object_ref import ObjectRef

        worker = self._worker()
        refs = [ObjectRef(i, worker=worker) for i in ids]
        ready, rest = worker.wait(refs, num_returns=num_returns,
                                  timeout=op_timeout,
                                  fetch_local=fetch_local)
        return [r.id for r in ready], [r.id for r in rest]

    def rpc_client_register_function(self, conn, blob: bytes):
        worker = self._worker()
        func_hash = hashlib.sha1(blob).digest()
        worker.gcs.call("kv_put", ns="funcs", key=func_hash, value=blob,
                        overwrite=False)
        return func_hash

    def rpc_client_submit_task(self, conn, func_hash: bytes, payload: bytes,
                               options: dict):
        args, kwargs = pickle.loads(payload)
        refs = self._worker().submit_task(func_hash, args, kwargs, **options)
        self._pin(conn, refs)
        # id AND owner travel back: the client re-pickles refs into later
        # task args, and dependency resolution needs the owner address
        return [(r.id, r.owner_addr) for r in refs]

    def rpc_client_create_actor(self, conn, class_hash: bytes,
                                payload: bytes, options: dict):
        args, kwargs = pickle.loads(payload)
        return self._worker().create_actor(class_hash, args, kwargs,
                                           options=options)

    def rpc_client_submit_actor_task(self, conn, actor_id: bytes,
                                     method_name: str, payload: bytes,
                                     options: dict):
        args, kwargs = pickle.loads(payload)
        refs = self._worker().submit_actor_task(actor_id, method_name,
                                                args, kwargs, **options)
        self._pin(conn, refs)
        return [(r.id, r.owner_addr) for r in refs]

    def rpc_client_cancel(self, conn, ref_id: bytes, force: bool):
        from ray_tpu._private.object_ref import ObjectRef

        worker = self._worker()
        worker.cancel_task(ObjectRef(ref_id, worker=worker), force=force)

    def rpc_client_gcs_call(self, conn, gcs_method: str, kw: dict):
        return self._worker().gcs.call(gcs_method, **kw)

    def rpc_client_kill(self, conn, actor_id: bytes, no_restart: bool):
        # runs the direct-dial kill from the server, which CAN reach raylets
        from ray_tpu._private.api import ActorHandle, kill

        kill(ActorHandle(actor_id), no_restart=no_restart)

    def rpc_client_available_resources(self, conn):
        from ray_tpu._private.api import available_resources

        return available_resources()

    def rpc_client_timeline(self, conn):
        from ray_tpu._private.api import timeline

        return timeline()

    def rpc_client_release(self, conn, ids: list):
        with self._lock:
            store = self._pinned.get(conn.id)
            if store is not None:
                for i in ids:
                    store.pop(i, None)


class ClientServer:
    """Serve the `ray://` protocol from this (already-initialized) driver
    process. ``ClientServer(port).start()``; clients connect with
    ``ray_tpu.init(address="ray://host:port")``."""

    def __init__(self, port: int = 10001, host: str = "0.0.0.0"):
        self._server = RpcServer(_ClientHandler(), host=host, port=port)

    @property
    def addr(self):
        return self._server.addr

    def start(self):
        self._server.start()
        return self

    def stop(self):
        self._server.stop()


_default_server: ClientServer | None = None


def serve(port: int = 10001, host: str = "0.0.0.0") -> ClientServer:
    """Start the process-wide client server (idempotent)."""
    global _default_server
    if _default_server is None:
        _default_server = ClientServer(port, host).start()
    return _default_server
