"""Client mode ("ray://") — drive a cluster through one proxy endpoint.

Reference: python/ray/util/client/ (~6k LoC; SURVEY.md §2.2 "Ray Client").
Server side: start `ClientServer` (or `serve()`) in any driver process.
Client side: ``ray_tpu.init(address="ray://host:port")``.
"""
from ray_tpu.util.client.client import ClientContext, connect
from ray_tpu.util.client.server import ClientServer, serve

__all__ = ["ClientContext", "ClientServer", "connect", "serve"]
