"""Parallel iterators over actor shards.

Reference: python/ray/util/iter.py (ParallelIterator / LocalIterator —
sharded lazy iterators held by actors, transformed with for_each/filter/
batch and consumed via gather_sync/gather_async). Useful as a lightweight
streaming alternative to Dataset when per-item order/laziness matters
(e.g. RL sample streams).
"""
from __future__ import annotations

import ray_tpu

# shard replies are wrapped tuples, never compared against user values
# (a plain sentinel compared with == would crash on numpy/pandas values
# and silently truncate shards that legitimately yield the sentinel)
_ITEM, _STOP = "item", "stop"


class _ShardActor:
    """Holds one shard's iterator + its transform chain."""

    def __init__(self, make_iter, transforms):
        self._make_iter = make_iter
        self._transforms = list(transforms)
        self._it = None

    def _build(self):
        it = iter(self._make_iter())
        for kind, fn in self._transforms:
            if kind == "for_each":
                it = map(fn, it)
            elif kind == "filter":
                it = filter(fn, it)
            elif kind == "batch":
                it = _batched(it, fn)
            elif kind == "flatten":
                it = (x for chunk in it for x in chunk)
        return it

    def next(self):
        if self._it is None:
            self._it = self._build()
        try:
            return (_ITEM, next(self._it))
        except StopIteration:
            return (_STOP, None)

    def reset(self):
        self._it = None


def _batched(it, n):
    batch = []
    for x in it:
        batch.append(x)
        if len(batch) == n:
            yield batch
            batch = []
    if batch:
        yield batch


class ParallelIterator:
    """A set of per-shard iterators living in actors; transforms are
    recorded lazily and run shard-local, only gathered values cross the
    cluster."""

    def __init__(self, shard_makers, transforms=()):
        self._shard_makers = list(shard_makers)
        self._transforms = list(transforms)

    # ------------------------------------------------------- transformations
    def _with(self, kind, fn) -> "ParallelIterator":
        return ParallelIterator(self._shard_makers,
                                self._transforms + [(kind, fn)])

    def for_each(self, fn) -> "ParallelIterator":
        return self._with("for_each", fn)

    def filter(self, fn) -> "ParallelIterator":
        return self._with("filter", fn)

    def batch(self, n: int) -> "ParallelIterator":
        return self._with("batch", n)

    def flatten(self) -> "ParallelIterator":
        return self._with("flatten", None)

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        if self._transforms != other._transforms:
            # materialize transforms into the shard makers via actors at
            # gather time; differing chains can't merge lazily
            raise ValueError("union requires identical transform chains; "
                             "call union before transforming, or gather")
        return ParallelIterator(self._shard_makers + other._shard_makers,
                                self._transforms)

    @property
    def num_shards(self) -> int:
        return len(self._shard_makers)

    # ------------------------------------------------------------- gathering
    def _spawn(self):
        actor_cls = ray_tpu.remote(_ShardActor)
        return [actor_cls.options(num_cpus=0).remote(mk, self._transforms)
                for mk in self._shard_makers]

    def gather_sync(self):
        """Round-robin over shards in order; stops when all exhaust."""
        actors = self._spawn()
        try:
            live = list(actors)
            while live:
                for actor in list(live):
                    kind, value = ray_tpu.get(actor.next.remote())
                    if kind == _STOP:
                        live.remove(actor)
                    else:
                        yield value
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    def gather_async(self, num_async: int = 1):
        """Yield values in completion order (reference: gather_async) —
        keeps `num_async` requests in flight per shard."""
        actors = self._spawn()
        try:
            inflight = {}
            for actor in actors:
                for _ in range(max(1, num_async)):
                    inflight[actor.next.remote()] = actor
            while inflight:
                ready, _ = ray_tpu.wait(list(inflight), num_returns=1,
                                        timeout=30)
                for ref in ready:
                    actor = inflight.pop(ref)
                    kind, value = ray_tpu.get(ref)
                    if kind == _STOP:
                        continue
                    inflight[actor.next.remote()] = actor
                    yield value
        finally:
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass

    # ------------------------------------------------------------- terminals
    def take(self, n: int) -> list:
        out = []
        for x in self.gather_sync():
            out.append(x)
            if len(out) >= n:
                break
        return out

    def count(self) -> int:
        return sum(1 for _ in self.gather_sync())

    def __repr__(self):
        return (f"ParallelIterator(shards={self.num_shards}, "
                f"transforms={len(self._transforms)})")


def from_items(items, num_shards: int = 2) -> ParallelIterator:
    shards = [list(items[i::num_shards]) for i in range(num_shards)]
    shards = [s for s in shards if s]

    def maker(shard):
        return lambda: iter(shard)

    return ParallelIterator([maker(s) for s in shards] or [lambda: iter(())])


def from_range(n: int, num_shards: int = 2) -> ParallelIterator:
    return from_items(list(range(n)), num_shards)


def from_iterators(makers) -> ParallelIterator:
    """Each element is a zero-arg callable returning an iterable — one
    shard each (generators themselves don't pickle; their factories do)."""
    return ParallelIterator(list(makers))
