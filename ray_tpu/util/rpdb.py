"""Remote pdb — break inside a task/actor and attach from anywhere
(reference: python/ray/util/rpdb.py — `ray debug` connects to a
socket-backed pdb the breakpoint opened; here `ray_tpu.util.rpdb
.set_trace()` listens on a TCP port, announces itself through GCS KV,
and `connect()` (or plain `nc host port`) attaches)."""
from __future__ import annotations

import pdb
import socket
import sys


class _SocketIO:
    """File-like adapter over one accepted connection."""

    def __init__(self, conn: socket.socket):
        self._file = conn.makefile("rw", buffering=1)

    def readline(self):
        return self._file.readline()

    def read(self, *a):
        return self._file.read(*a)

    def write(self, data):
        try:
            self._file.write(data)
        except OSError:
            pass
        return len(data)

    def flush(self):
        try:
            self._file.flush()
        except OSError:
            pass


class RemotePdb(pdb.Pdb):
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(1)
        self.addr = self._listener.getsockname()
        self._attached = False
        self._announce()
        # heartbeat while waiting: active_sessions prunes entries whose
        # ts goes stale (a killed task can't clean up after itself)
        import threading

        def _beat():
            import time as _t

            while not self._attached:
                _t.sleep(5.0)
                if not self._attached:
                    self._announce()

        threading.Thread(target=_beat, daemon=True).start()
        print(f"[rpdb] waiting for debugger on "
              f"{self.addr[0]}:{self.addr[1]} "
              f"(nc {self.addr[0]} {self.addr[1]})",
              file=sys.stderr, flush=True)
        self._conn, _ = self._listener.accept()
        self._attached = True
        self._withdraw()   # a session list shows WAITING breakpoints
        io = _SocketIO(self._conn)
        super().__init__(stdin=io, stdout=io)
        self.prompt = "(rpdb) "

    def _announce(self):
        """Register in GCS KV so `active_sessions()` finds us."""
        try:
            import json
            import os

            from ray_tpu._private.worker_runtime import current_worker

            w = current_worker()
            if w is not None:
                # announce a ROUTABLE host: the bind address (loopback /
                # 0.0.0.0) is meaningless from other nodes — the
                # worker's registered RPC address is how peers reach
                # this host
                host = self.addr[0]
                if host in ("0.0.0.0", "127.0.0.1") and w.addr:
                    host = w.addr[0]
                import time

                w.gcs.call(
                    "kv_put", ns="rpdb",
                    key=f"{os.getpid()}".encode(),
                    value=json.dumps({
                        "host": host, "port": self.addr[1],
                        "pid": os.getpid(), "ts": time.time(),
                        "worker_id": w.worker_id}).encode(),
                    timeout=5.0)
        except Exception:
            pass   # debugging must work even when the runtime is down

    def _withdraw(self):
        try:
            import os

            from ray_tpu._private.worker_runtime import current_worker

            w = current_worker()
            if w is not None:
                w.gcs.call("kv_del", ns="rpdb",
                           key=f"{os.getpid()}".encode(), timeout=5.0)
        except Exception:
            pass

    def close(self):
        try:
            self._conn.close()
        finally:
            self._listener.close()

    # session-over hooks: 'c' (with no breakpoints) or 'q' ends the
    # remote session — close the sockets so the client sees EOF and a
    # looping breakpoint can't leak fds
    def set_continue(self):
        super().set_continue()
        if not self.breaks:
            self.close()

    def set_quit(self):
        super().set_quit()
        self.close()


def set_trace(host: str = "127.0.0.1", port: int = 0):
    """Open a remote breakpoint at the caller's frame and BLOCK until a
    debugger attaches (parity: ray.util.rpdb.set_trace). The session's
    sockets close when the debugger continues/quits (set_continue/
    set_quit hooks) — the client gets EOF and repeated breakpoints don't
    leak fds. NOTE: pdb.set_trace installs tracing and returns; closing
    here would kill the session before the first prompt."""
    rdb = RemotePdb(host, port)
    rdb.set_trace(sys._getframe().f_back)


def active_sessions(address: str | None = None) -> list[dict]:
    """Breakpoints currently WAITING across the cluster. Entries whose
    listener no longer answers (task cancelled / worker killed before
    any attach) are pruned from the KV as they are discovered — a crash
    can't clean up after itself, so the listing does."""
    import json

    from ray_tpu.experimental.state.api import _gcs

    import time

    out = []
    with _gcs(address) as call:
        for key in call("kv_keys", ns="rpdb"):
            blob = call("kv_get", ns="rpdb", key=key)
            if not blob:
                continue
            info = json.loads(blob)
            # liveness via the entry's heartbeat (the waiting breakpoint
            # refreshes `ts` every few seconds; a TCP probe would be
            # DESTRUCTIVE — it would consume the single accept slot and
            # bind the pdb session to the probe)
            if time.time() - info.get("ts", 0) > 20.0:
                call("kv_del", ns="rpdb", key=key)   # stale entry
                continue
            out.append(info)
    return out


def connect(host: str, port: int):
    """Interactive attach: bridge this terminal to a waiting breakpoint
    (the `ray debug` role; `nc host port` works equally)."""
    sock = socket.create_connection((host, int(port)), timeout=10)
    f = sock.makefile("rw", buffering=1)
    import threading

    def pump_out():
        # byte-wise: the '(rpdb) ' prompt carries no newline, so a
        # line-buffered pump would never show it
        try:
            while True:
                data = sock.recv(4096)
                if not data:
                    break
                sys.stdout.write(data.decode(errors="replace"))
                sys.stdout.flush()
        except OSError:
            pass

    t = threading.Thread(target=pump_out, daemon=True)
    t.start()
    try:
        for line in sys.stdin:
            f.write(line)
            f.flush()
    except (KeyboardInterrupt, OSError):
        pass
    finally:
        sock.close()
