"""Placement groups — gang reservation of resource bundles across nodes.

Reference: python/ray/util/placement_group.py (placement_group() at :128,
PlacementGroup.ready/wait at :33, remove at :233) and the GCS-side 2-phase
scheduler (src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h). The
TPU-relevant extension is that bundles carrying a "TPU" resource are packed
onto nodes within one ICI domain when possible (v1: node-level packing; slice
topology awareness lands with the multi-host scheduler).
"""
from __future__ import annotations

import os
import time

from ray_tpu._private import api
from ray_tpu.exceptions import PlacementGroupUnschedulableError

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


class PlacementGroup:
    def __init__(self, pg_id: bytes):
        self.id = pg_id

    def ready(self):
        """ObjectRef resolving when the PG is created (reference returns a
        ref from an internal task; we do the same with a waiter task)."""
        pg_id = self.id

        @api.remote
        def _pg_ready_waiter():
            # runs on any worker; PG readiness is a GCS question
            from ray_tpu._private.worker_runtime import current_worker

            worker = current_worker()
            deadline = time.time() + 300.0
            while time.time() < deadline:
                snap = worker.gcs.call("get_placement_group", pg_id=pg_id)
                if snap and snap["State"] == "CREATED":
                    return True
                time.sleep(0.05)
            raise PlacementGroupUnschedulableError(
                f"placement group {pg_id.hex()} not schedulable")

        return _pg_ready_waiter.options(num_cpus=0.0).remote()

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        worker = api._require_worker()
        deadline = time.time() + timeout_seconds
        while time.time() < deadline:
            snap = worker.gcs.call("get_placement_group", pg_id=self.id)
            if snap and snap["State"] == "CREATED":
                return True
            time.sleep(0.05)
        return False

    @property
    def bundle_specs(self):
        worker = api._require_worker()
        snap = worker.gcs.call("get_placement_group", pg_id=self.id)
        return snap["Bundles"] if snap else []

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id,))


def placement_group(bundles: list[dict], strategy: str = "PACK",
                    name: str = "", lifetime=None) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    worker = api._require_worker()
    pg_id = os.urandom(16)
    worker.gcs.call("create_placement_group", pg_id=pg_id,
                    bundles=[{k: float(v) for k, v in b.items()}
                             for b in bundles],
                    strategy=strategy, name=name)
    return PlacementGroup(pg_id)


def remove_placement_group(pg: PlacementGroup):
    worker = api._require_worker()
    worker.gcs.call("remove_placement_group", pg_id=pg.id)


def get_placement_group(name: str) -> PlacementGroup:
    worker = api._require_worker()
    snap = worker.gcs.call("get_placement_group", name=name)
    if snap is None:
        raise ValueError(f"placement group {name!r} not found")
    return PlacementGroup(bytes.fromhex(snap["PlacementGroupID"]))


def placement_group_table():
    worker = api._require_worker()
    return {s["PlacementGroupID"]: s
            for s in worker.gcs.call("list_placement_groups")}


def get_current_placement_group():
    return None   # capture of child tasks into the caller's PG: not yet
