"""Placement groups — gang reservation of resource bundles across nodes.

Reference: python/ray/util/placement_group.py (placement_group() at :128,
PlacementGroup.ready/wait at :33, remove at :233) and the GCS-side 2-phase
scheduler (src/ray/gcs/gcs_server/gcs_placement_group_scheduler.h). The
TPU-relevant extension is that bundles carrying a "TPU" resource are packed
onto nodes within one ICI domain when possible (v1: node-level packing; slice
topology awareness lands with the multi-host scheduler).
"""
from __future__ import annotations

import os
import threading
import time

from ray_tpu._private import api
from ray_tpu.exceptions import PlacementGroupUnschedulableError

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD",
                    "SPREAD_ACROSS_SLICES")


class PlacementGroup:
    def __init__(self, pg_id: bytes):
        self.id = pg_id

    def ready(self):
        """ObjectRef resolving when the PG is created (reference returns a
        ref from an internal task; we do the same with a waiter task)."""
        pg_id = self.id

        @api.remote
        def _pg_ready_waiter():
            # runs on any worker; PG readiness is a GCS question — the
            # waiter rides the same pg_state subscription wait() uses
            if not PlacementGroup(pg_id).wait(300.0):
                raise PlacementGroupUnschedulableError(
                    f"placement group {pg_id.hex()} not schedulable")
            return True

        return _pg_ready_waiter.options(num_cpus=0.0).remote()

    def wait(self, timeout_seconds: float = 30.0, *,
             _created_event: "threading.Event | None" = None) -> bool:
        """Block until the PG is CREATED (or timeout). Rides the GCS's
        ``pg_state`` pubsub channel — the waiter wakes on the CREATED
        push instead of hammering `get_placement_group` at 20 Hz — with
        PR 12's snapshot-resync covering feed gaps, and a direct-RPC
        poll kept underneath as FALLBACK (`pg_wait_poll_fallback_s`
        cadence) so a missed transition can never hang the waiter. The
        fallback poll doubles as the lazy scheduling kick for clusters
        whose capacity events are sparse.

        ``_created_event`` (internal): an Event some existing pg_state
        subscription sets on this PG's CREATED — callers that already
        hold one (the Train plane's preemption monitor) reuse it
        instead of paying a second dedicated GCS subscription per gang
        start."""
        from ray_tpu._private.config import get_config

        worker = api._require_worker()
        snap = worker.gcs.call("get_placement_group", pg_id=self.id)
        if snap and snap["State"] == "CREATED":
            return True
        deadline = time.time() + timeout_seconds
        created = _created_event if _created_event is not None \
            else threading.Event()
        pg_id = self.id

        def _on_msg(msg):
            if not isinstance(msg, dict):
                return
            if msg.get("event") == "resync":
                for row in (msg.get("snapshot") or ()):
                    if isinstance(row, dict) and row.get("pg_id") == pg_id \
                            and row.get("state") == "CREATED":
                        created.set()
            elif msg.get("event") == "state" and msg.get("pg_id") == pg_id \
                    and msg.get("state") == "CREATED":
                created.set()

        watch = None
        poll_s = max(0.05, float(get_config("pg_wait_poll_fallback_s")))
        if _created_event is None:
            try:
                from ray_tpu._private.pubsub import watch_channel

                watch = watch_channel("pg_state", _on_msg,
                                      worker.gcs.addr, poll_timeout=2.0)
            except Exception:
                # no pubsub (degraded GCS): poll at the legacy cadence
                poll_s = 0.05
        try:
            while True:
                # poll first: it closes the race where the transition
                # landed between the entry snapshot and the subscribe
                snap = worker.gcs.call("get_placement_group",
                                       pg_id=self.id)
                if (snap and snap["State"] == "CREATED") \
                        or created.is_set():
                    return True
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                if created.wait(min(poll_s, remaining)):
                    return True
        finally:
            if watch is not None:
                watch.stop()

    @property
    def bundle_specs(self):
        worker = api._require_worker()
        snap = worker.gcs.call("get_placement_group", pg_id=self.id)
        return snap["Bundles"] if snap else []

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id,))


def placement_group(bundles: list[dict], strategy: str = "PACK",
                    name: str = "", lifetime=None,
                    job: str | None = None,
                    bundle_stages: list | None = None) -> PlacementGroup:
    """``job`` labels the gang for the multi-tenant scheduling plane
    (quota accounting, fair share, priority preemption —
    ``ray_tpu.util.jobs``); omitted, it inherits this process's current
    job (``jobs.set_current_job``).

    ``bundle_stages`` (SPREAD_ACROSS_SLICES) labels each bundle with its
    pipeline stage: bundles sharing a label form one stage sub-gang that
    lands contiguous inside ONE slice, distinct stages land on distinct
    slices (the multi-slice MPMD layout — inner collectives ride ICI,
    inter-stage activations hop the inter-slice plane). Omitted, every
    bundle is its own stage. Placement is all-or-nothing: a gang that
    cannot place every stage this way stays PENDING whole."""
    if strategy not in VALID_STRATEGIES:
        raise ValueError(
            f"strategy must be one of {VALID_STRATEGIES}, got {strategy!r}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty "
                         "resource dicts")
    if bundle_stages is not None and len(bundle_stages) != len(bundles):
        raise ValueError(
            f"bundle_stages must label every bundle: got "
            f"{len(bundle_stages)} labels for {len(bundles)} bundles")
    if job is None:
        from ray_tpu.util import jobs as _jobs

        job = _jobs.current_job()
    worker = api._require_worker()
    pg_id = os.urandom(16)
    worker.gcs.call("create_placement_group", pg_id=pg_id,
                    bundles=[{k: float(v) for k, v in b.items()}
                             for b in bundles],
                    strategy=strategy, name=name, job=job or "",
                    stages=(list(bundle_stages)
                            if bundle_stages is not None else None))
    return PlacementGroup(pg_id)


def remove_placement_group(pg: PlacementGroup):
    worker = api._require_worker()
    worker.gcs.call("remove_placement_group", pg_id=pg.id)


def get_placement_group(name: str) -> PlacementGroup:
    worker = api._require_worker()
    snap = worker.gcs.call("get_placement_group", name=name)
    if snap is None:
        raise ValueError(f"placement group {name!r} not found")
    return PlacementGroup(bytes.fromhex(snap["PlacementGroupID"]))


def placement_group_table():
    worker = api._require_worker()
    return {s["PlacementGroupID"]: s
            for s in worker.gcs.call("list_placement_groups")}


def get_current_placement_group():
    return None   # capture of child tasks into the caller's PG: not yet
