"""ray_tpu.util — cluster utilities layered on the core API
(reference: python/ray/util/__init__.py)."""
from __future__ import annotations


def list_named_actors(all_namespaces: bool = False) -> list:
    """Names of live named actors (reference: util/__init__.py
    list_named_actors). Returns names in the current namespace, or
    [{"name", "namespace"}] dicts with all_namespaces=True."""
    from ray_tpu._private.api import _namespace, _require_worker

    worker = _require_worker()
    # inside an actor, the driver's init(namespace=...) never ran in this
    # process — the actor's own spec carries the effective namespace
    ns = _namespace
    spec = getattr(worker, "_actor_spec", None)
    if spec and spec.get("namespace"):
        ns = spec["namespace"]
    rows = worker.gcs.call(
        "list_named_actors", all_namespaces=all_namespaces,
        namespace=ns)
    if all_namespaces:
        return rows
    return [r["name"] for r in rows]


__all__ = ["list_named_actors"]
