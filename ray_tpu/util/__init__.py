"""ray_tpu.util — cluster utilities layered on the core API
(reference: python/ray/util/__init__.py)."""
from __future__ import annotations


def list_named_actors(all_namespaces: bool = False) -> list:
    """Names of live named actors (reference: util/__init__.py
    list_named_actors). Returns names in the current namespace, or
    [{"name", "namespace"}] dicts with all_namespaces=True."""
    from ray_tpu._private.api import _namespace, _require_worker

    rows = _require_worker().gcs.call(
        "list_named_actors", all_namespaces=all_namespaces,
        namespace=_namespace)
    if all_namespaces:
        return rows
    return [r["name"] for r in rows]


__all__ = ["list_named_actors"]
