"""Distributed tracing: spans propagated through task/actor calls.

Reference: python/ray/util/tracing/tracing_helper.py:290 — Ray injects
OpenTelemetry spans through the TaskSpec so a driver's trace continues
inside remote execution (submit span on the caller, execute span on the
worker, linked by parent ids). The OpenTelemetry SDK is not bundled
here, so this module implements the same propagation natively with
W3C-trace-context-shaped ids (128-bit trace id, 64-bit span ids) and
exports OTLP-shaped JSON any collector/Jaeger can ingest — plugging the
real SDK in later is a TracerProvider swap, not a redesign.

Usage::

    from ray_tpu.util import tracing
    tracing.enable()
    ray_tpu.get(f.remote())          # spans recorded on every hop
    spans = tracing.get_spans()      # cluster-wide fan-out
    tracing.export_otlp_json(spans, "trace.json")

Propagation is implicit once a context exists: a worker executing a
traced task records spans (and propagates to nested submissions) even
if it never called enable() itself — exactly the reference's behavior
where the TaskSpec carries the context.
"""
from __future__ import annotations

import collections
import contextlib
import contextvars
import json
import os
import threading
import time

_MAX_SPANS = 10_000

# cached per process (workers are spawned, not forked): getpid/uname are
# real syscalls on this container runtime — measurable per-span cost
_PID = os.getpid()
_NODE = os.uname().nodename

_lock = threading.Lock()
_spans: collections.deque = collections.deque(maxlen=_MAX_SPANS)
_dropped = 0
_enabled = False

# the active span for THIS logical execution context (task body, driver
# code path); contextvars keep concurrent actor calls separate
_current: contextvars.ContextVar = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)


def enable():
    global _enabled
    _enabled = True


def disable():
    global _enabled
    _enabled = False


def is_enabled() -> bool:
    return _enabled or _current.get() is not None


def _new_id(nbytes: int) -> str:
    return os.urandom(nbytes).hex()


def _append_span(span: dict):
    """Sole writer to the ring: a span pushed into a FULL ring evicts
    the oldest one, and that loss is COUNTED (metric + stats) — fused
    consumers (step anatomy, flight recorder) must be able to flag an
    incomplete window instead of silently reporting wrong attribution."""
    global _dropped
    with _lock:
        dropped = len(_spans) == _spans.maxlen
        if dropped:
            _dropped += 1
        _spans.append(span)
    if dropped:
        try:
            from ray_tpu._private import telemetry as _tm

            _tm.counter_inc("ray_tpu_trace_dropped_total")
        except Exception:
            pass


def current_context() -> dict | None:
    """{"trace_id", "span_id"} of the active span, or None."""
    return _current.get()


def inject_context() -> dict | None:
    """Context to attach to an outgoing task/actor spec. Starts a new
    trace at the root when tracing is enabled and no span is active."""
    ctx = _current.get()
    if ctx is not None:
        return {"trace_id": ctx["trace_id"],
                "parent_span_id": ctx["span_id"]}
    if _enabled:
        return {"trace_id": _new_id(16), "parent_span_id": None}
    return None


@contextlib.contextmanager
def span(name: str, kind: str, ctx: dict | None = None,
         attributes: dict | None = None):
    """Record one span. `ctx` (an injected context) links the span into
    an existing trace; otherwise it continues the current one."""
    if ctx is None:
        inherited = _current.get()
        if inherited is None:
            if not _enabled:
                yield None
                return
            trace_id, parent = _new_id(16), None
        else:
            trace_id, parent = inherited["trace_id"], inherited["span_id"]
    else:
        trace_id = ctx["trace_id"]
        parent = ctx.get("parent_span_id")
    span_id = _new_id(8)
    token = _current.set({"trace_id": trace_id, "span_id": span_id})
    start = time.time_ns()
    try:
        yield {"trace_id": trace_id, "span_id": span_id}
    finally:
        end = time.time_ns()
        _current.reset(token)
        _append_span({
            "traceId": trace_id,
            "spanId": span_id,
            "parentSpanId": parent,
            "name": name,
            "kind": kind,                # "PRODUCER"/"CONSUMER"/...
            "startTimeUnixNano": start,
            "endTimeUnixNano": end,
            "pid": _PID,
            # pids collide across hosts; (node, pid) identifies the
            # producing process cluster-wide
            "node": _NODE,
            "attributes": attributes or {},
        })


def record_completed_span(name: str, kind: str, start_ns: int,
                          end_ns: int, attributes: dict | None = None,
                          ctx: dict | None = None):
    """Append an already-timed span linked under the CURRENT context
    (same linkage rule as span(); no-op when tracing is inactive).
    For observers that only learn a span happened after the fact —
    e.g. a compile-cache miss detected by cache-size delta — so the
    span can't wrap the work as a context manager. An explicit ``ctx``
    (an injected context, e.g. captured at @serve.batch enqueue time on
    the CALLER's thread) overrides the current-context linkage — the
    recording thread's own context is usually the wrong trace there."""
    if ctx is not None:
        trace_id, parent = ctx["trace_id"], ctx.get("parent_span_id")
    else:
        inherited = _current.get()
        if inherited is None:
            if not _enabled:
                return None
            trace_id, parent = _new_id(16), None
        else:
            trace_id, parent = inherited["trace_id"], inherited["span_id"]
    span_id = _new_id(8)
    _append_span({
        "traceId": trace_id,
        "spanId": span_id,
        "parentSpanId": parent,
        "name": name,
        "kind": kind,
        "startTimeUnixNano": int(start_ns),
        "endTimeUnixNano": int(end_ns),
        "pid": _PID,
        "node": _NODE,
        "attributes": attributes or {},
    })
    return {"trace_id": trace_id, "span_id": span_id}


def submit_span(spec: dict, name: str):
    """Context manager for an outgoing task/actor submission: opens the
    PRODUCER span (enclosing the submission work — arg pinning, queue
    handoff — so its duration is meaningful), and injects the context
    into ``spec["trace_ctx"]`` so the remote execute span becomes its
    child. No-op (null context) when tracing is inactive. One helper so
    task and actor submission can't drift apart."""
    ctx = inject_context()
    if ctx is None:
        return contextlib.nullcontext()

    @contextlib.contextmanager
    def _cm():
        with span(f"submit {name}", "PRODUCER", ctx,
                  {"task_id": spec["task_id"].hex()}) as sp:
            spec["trace_ctx"] = {"trace_id": sp["trace_id"],
                                 "parent_span_id": sp["span_id"]}
            yield sp

    return _cm()


def local_spans(with_drop_marker: bool = False) -> list[dict]:
    """This process's spans. ``with_drop_marker=True`` (the RPC path)
    appends one marker entry carrying this process's drop count so
    cluster collection can surface ring overflow; ``get_spans`` strips
    markers back out of the span list."""
    with _lock:
        out = list(_spans)
        dropped = _dropped
    if with_drop_marker and dropped:
        out.append({"spanId": f"__drops__:{_NODE}:{_PID}",
                    "__drops__": dropped, "node": _NODE, "pid": _PID})
    return out


def stats() -> dict:
    with _lock:
        return {"buffered": len(_spans), "dropped": _dropped,
                "capacity": _spans.maxlen}


def clear():
    global _dropped
    with _lock:
        _spans.clear()
        _dropped = 0


class SpanList(list):
    """``get_spans``'s return type: a plain span list, plus ``dropped``
    — {(node, pid): count} of spans each process's ring evicted before
    collection. A non-empty ``dropped`` means the trace window is
    incomplete and fused attribution over it should say so."""

    def __init__(self, spans, dropped):
        super().__init__(spans)
        self.dropped: dict[tuple, int] = dropped

    @property
    def complete(self) -> bool:
        return not self.dropped


def get_spans(address: str | None = None) -> "SpanList":
    """Cluster-wide span collection: driver-local spans plus a fan-out
    over every raylet's workers (the same plumbing as `timeline()`).
    Returns a list subclass whose ``dropped`` maps (node, pid) to the
    spans that process's ring evicted (incomplete-window signal)."""
    out = local_spans(with_drop_marker=True)
    try:
        from ray_tpu.experimental.state.api import _each_raylet, _gcs

        with _gcs(address) as call:
            out.extend(_each_raylet(call, "trace_spans"))
    except Exception:
        # a partial trace must not masquerade as a complete one
        import logging

        logging.getLogger(__name__).warning(
            "cluster span fan-out failed; returning driver-local spans "
            "only", exc_info=True)
    # the driver's own worker also answers the fan-out — dedup by span id
    seen, deduped = set(), []
    drops: dict[tuple, int] = {}
    for s in out:
        if s["spanId"] in seen:
            continue
        seen.add(s["spanId"])
        if "__drops__" in s:
            drops[(s.get("node"), s.get("pid"))] = s["__drops__"]
            continue
        deduped.append(s)
    return SpanList(deduped, drops)


def export_otlp_json(spans: list[dict], path: str) -> str:
    """OTLP/JSON export (the shape `otelcol`'s file receiver and Jaeger's
    OTLP ingestion accept): one resourceSpans entry per producing
    (node, pid) — pid alone collides across hosts."""
    by_proc: dict[tuple, list] = {}
    for s in spans:
        by_proc.setdefault((s.get("node", ""), s.get("pid", 0)),
                           []).append(s)
    doc = {"resourceSpans": [
        {
            "resource": {"attributes": [
                {"key": "service.name",
                 "value": {"stringValue": "ray_tpu"}},
                {"key": "host.name",
                 "value": {"stringValue": node}},
                {"key": "process.pid",
                 "value": {"intValue": pid}},
            ]},
            "scopeSpans": [{
                "scope": {"name": "ray_tpu.util.tracing"},
                "spans": [{
                    "traceId": s["traceId"],
                    "spanId": s["spanId"],
                    **({"parentSpanId": s["parentSpanId"]}
                       if s.get("parentSpanId") else {}),
                    "name": s["name"],
                    "kind": {"PRODUCER": 4, "CONSUMER": 5}.get(
                        s.get("kind", ""), 1),
                    "startTimeUnixNano": str(s["startTimeUnixNano"]),
                    "endTimeUnixNano": str(s["endTimeUnixNano"]),
                    "attributes": [
                        {"key": str(k), "value": {"stringValue": str(v)}}
                        for k, v in (s.get("attributes") or {}).items()],
                } for s in group],
            }],
        } for (node, pid), group in sorted(by_proc.items())
    ]}
    with open(path, "w") as f:
        json.dump(doc, f)
    return path
