"""joblib backend — scikit-learn parallelism on the runtime's tasks.

Reference: python/ray/util/joblib/ (register_ray + RayBackend over the
actor pool). ``register_ray()`` then ``joblib.parallel_backend("ray")``
routes every joblib batch (e.g. a GridSearchCV fit) through remote
tasks, so sklearn workloads fan out over the cluster.
"""
from __future__ import annotations

import threading


def register_ray():
    from joblib.parallel import ParallelBackendBase, register_parallel_backend

    class _AsyncResult:
        def __init__(self, ref, callback):
            self._ref = ref
            self._callback = callback
            self._value = None
            self._done = threading.Event()

        def _resolve(self):
            import ray_tpu

            try:
                self._value = ray_tpu.get(self._ref)
            except BaseException as e:  # noqa: BLE001
                self._value = e
            self._done.set()
            if self._callback is not None:
                self._callback(self._value)

        def get(self, timeout=None):
            if not self._done.wait(timeout):
                raise TimeoutError("joblib task timed out")
            if isinstance(self._value, BaseException):
                raise self._value
            return self._value

    class _Waiter:
        """One shared thread drains completions for every in-flight batch
        (instead of a blocked thread per batch)."""

        def __init__(self):
            self._lock = threading.Lock()
            self._pending: dict = {}          # ref -> _AsyncResult
            self._thread = None

        def add(self, result: "_AsyncResult"):
            with self._lock:
                self._pending[result._ref] = result
                if self._thread is None or not self._thread.is_alive():
                    self._thread = threading.Thread(
                        target=self._loop, daemon=True,
                        name="joblib-ray-waiter")
                    self._thread.start()

        def _loop(self):
            import ray_tpu

            consecutive_errors = 0
            while True:
                with self._lock:
                    refs = list(self._pending)
                    if not refs:
                        self._thread = None
                        return
                try:
                    ready, _ = ray_tpu.wait(refs,
                                            num_returns=1, timeout=0.2)
                    consecutive_errors = 0
                except BaseException as e:  # noqa: BLE001
                    consecutive_errors += 1
                    if consecutive_errors < 5:
                        import time as _time

                        _time.sleep(0.2)
                        continue
                    # the runtime is gone: fail every pending result so
                    # joblib.Parallel raises instead of hanging forever
                    with self._lock:
                        pending = list(self._pending.values())
                        self._pending.clear()
                        self._thread = None
                    for result in pending:
                        result._value = e
                        result._done.set()
                        if result._callback is not None:
                            try:
                                result._callback(e)
                            except Exception:
                                pass
                    return
                for ref in ready:
                    with self._lock:
                        result = self._pending.pop(ref, None)
                    if result is not None:
                        result._resolve()

        def cancel_all(self):
            import ray_tpu

            with self._lock:
                refs = list(self._pending)
            for ref in refs:
                try:
                    ray_tpu.cancel(ref)
                except Exception:
                    pass

    class RayBackend(ParallelBackendBase):
        supports_timeout = True
        default_n_jobs = -1

        def configure(self, n_jobs=1, parallel=None, **_):
            self.parallel = parallel
            return self.effective_n_jobs(n_jobs)

        def effective_n_jobs(self, n_jobs):
            import ray_tpu

            if not ray_tpu.is_initialized():
                return 1
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            return cpus if n_jobs in (-1, None) else min(n_jobs, cpus)

        def apply_async(self, func, callback=None):
            import ray_tpu

            if not hasattr(self, "_task"):
                self._task = ray_tpu.remote(lambda f: f())
                self._waiter = _Waiter()
            result = _AsyncResult(self._task.remote(func), callback)
            self._waiter.add(result)
            return result

        def abort_everything(self, ensure_ready=True):
            # cancel outstanding remote batches so a failed fit doesn't
            # leave hours of work running in the background
            waiter = getattr(self, "_waiter", None)
            if waiter is not None:
                waiter.cancel_all()

    register_parallel_backend("ray", RayBackend)


__all__ = ["register_ray"]
