"""Serializability inspection (reference: python/ray/util/check_serialize.py
``inspect_serializability`` — walks an object that fails cloudpickle and
names the inner members that are the actual culprits)."""
from __future__ import annotations

import inspect
from dataclasses import dataclass, field


@dataclass
class FailureTuple:
    obj: object
    name: str
    parent: str

    def __repr__(self):
        return f"FailureTuple({self.name!r} [in {self.parent!r}])"


@dataclass
class _Result:
    serializable: bool
    failures: list = field(default_factory=list)


def _try_dumps(obj) -> bool:
    import cloudpickle

    try:
        cloudpickle.dumps(obj)
        return True
    except Exception:
        return False


def _inspect(obj, name: str, depth: int, failures: list, seen: set):
    if id(obj) in seen:
        return
    if depth <= 0:
        # Depth budget exhausted: name this object rather than reporting
        # "unserializable" with no culprit at all. NOT added to `seen` —
        # a later visit via a shorter path still deserves a full walk.
        failures.append(FailureTuple(obj, name, name))
        return
    seen.add(id(obj))
    found_inner = False
    # closures: the usual culprit for functions
    if inspect.isfunction(obj):
        closure = obj.__closure__ or ()
        for var, cell in zip(obj.__code__.co_freevars, closure):
            try:
                inner = cell.cell_contents
            except ValueError:
                continue
            if not _try_dumps(inner):
                found_inner = True
                _inspect(inner, var, depth - 1, failures, seen)
        for var, val in (obj.__globals__ or {}).items():
            if var in obj.__code__.co_names and not _try_dumps(val):
                found_inner = True
                _inspect(val, var, depth - 1, failures, seen)
    elif hasattr(obj, "__dict__") and isinstance(obj.__dict__, dict):
        for attr, val in obj.__dict__.items():
            if not _try_dumps(val):
                found_inner = True
                _inspect(val, f"{name}.{attr}", depth - 1, failures, seen)
    elif isinstance(obj, (list, tuple, set)):
        for i, item in enumerate(obj):
            if not _try_dumps(item):
                found_inner = True
                _inspect(item, f"{name}[{i}]", depth - 1, failures, seen)
    elif isinstance(obj, dict):
        for k, v in obj.items():
            if not _try_dumps(v):
                found_inner = True
                _inspect(v, f"{name}[{k!r}]", depth - 1, failures, seen)
    if not found_inner:
        # this object itself is the leaf culprit
        failures.append(FailureTuple(obj, name, name))


def inspect_serializability(obj, name: str | None = None,
                            depth: int = 3,
                            print_file=None) -> tuple[bool, set]:
    """Returns (serializable, failure_set). When not serializable, the
    failure set names the innermost unserializable members."""
    name = name or getattr(obj, "__name__", repr(obj)[:40])
    if _try_dumps(obj):
        return True, set()
    failures: list = []
    _inspect(obj, name, depth, failures, set())
    fail_set = {f.name for f in failures}
    msg = (f"{name!r} is not serializable; offending members: "
           f"{sorted(fail_set)}")
    if print_file is not None:
        print(msg, file=print_file)
    else:
        print(msg)
    return False, fail_set
