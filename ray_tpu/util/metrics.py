"""User-facing metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py (backed by OpenCensus → dashboard
agent → Prometheus, reporter_agent.py:296). Here each process keeps a
registry; `ray_tpu.experimental.state.api.metrics_summary()` aggregates
across live workers, and `prometheus_text()` renders the standard text
exposition format for scraping.
"""
from __future__ import annotations

import threading

_lock = threading.Lock()
_registry: dict[str, "_Metric"] = {}


class _Metric:
    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple = ()):
        if not name or any(c in name for c in " \t\n"):
            raise ValueError(f"bad metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._values: dict[tuple, float] = {}
        with _lock:
            existing = _registry.get(name)
            if existing is not None and type(existing) is not type(self):
                raise ValueError(
                    f"metric {name!r} already registered with a different "
                    f"type")
            _registry[name] = self

    def set_default_tags(self, tags: dict):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: dict | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def snapshot(self) -> dict:
        with _lock:
            return {
                "name": self.name,
                "type": type(self).__name__,
                "description": self.description,
                "values": [{"tags": dict(k), "value": v}
                           for k, v in self._values.items()],
            }


class Counter(_Metric):
    def inc(self, value: float = 1.0, tags: dict | None = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._key(tags)
        with _lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(_Metric):
    def set(self, value: float, tags: dict | None = None):
        with _lock:
            self._values[self._key(tags)] = float(value)


class Histogram(_Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: list | None = None, tag_keys: tuple = ()):
        super().__init__(name, description, tag_keys)
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100])
        self._counts: dict[tuple, list] = {}
        self._sums: dict[tuple, float] = {}

    def observe(self, value: float, tags: dict | None = None):
        key = self._key(tags)
        with _lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            import bisect

            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._values[key] = self._sums[key]   # exported as _sum

    def snapshot(self) -> dict:
        base = super().snapshot()
        with _lock:
            base["boundaries"] = self.boundaries
            base["counts"] = [{"tags": dict(k), "counts": v}
                              for k, v in self._counts.items()]
        return base


def registry_snapshot() -> list[dict]:
    with _lock:
        metrics = list(_registry.values())
    return [m.snapshot() for m in metrics]


def _label(tags: dict, extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in tags.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(snapshots: list[dict]) -> str:
    """Standard Prometheus text exposition of aggregated snapshots.
    Histograms emit the full family: cumulative _bucket{le=...}, _count,
    and _sum series."""
    lines = []
    for snap in snapshots:
        name = snap["name"]
        kind = {"Counter": "counter", "Gauge": "gauge",
                "Histogram": "histogram"}.get(snap["type"], "untyped")
        if snap.get("description"):
            lines.append(f"# HELP {name} {snap['description']}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            bounds = snap.get("boundaries", [])
            sums = {tuple(sorted(r["tags"].items())): r["value"]
                    for r in snap["values"]}
            for row in snap.get("counts", []):
                tags = row["tags"]
                counts = row["counts"]
                cum = 0
                for b, c in zip(bounds, counts):
                    cum += c
                    lines.append(
                        f"{name}_bucket{_label(tags, f'le=\"{b}\"')} {cum}")
                cum += counts[len(bounds)] if len(counts) > len(bounds) else 0
                lines.append(
                    f"{name}_bucket{_label(tags, 'le=\"+Inf\"')} {cum}")
                lines.append(f"{name}_count{_label(tags)} {cum}")
                key = tuple(sorted(tags.items()))
                lines.append(f"{name}_sum{_label(tags)} "
                             f"{sums.get(key, 0.0)}")
        else:
            for row in snap["values"]:
                lines.append(f"{name}{_label(row['tags'])} {row['value']}")
    return "\n".join(lines) + "\n"
