"""User-facing metrics: Counter / Gauge / Histogram.

Reference: python/ray/util/metrics.py (backed by OpenCensus → dashboard
agent → Prometheus, reporter_agent.py:296). Here each process keeps a
registry; `ray_tpu.experimental.state.api.metrics_summary()` aggregates
across live workers (summing counters/histograms per tag set via
`aggregate_snapshots`), and `prometheus_text()` renders the standard
text exposition format for scraping.

Re-instantiating a metric with an already-registered name and the SAME
type returns the live registered instance (a fresh object would silently
drop every accumulated value — e.g. an actor re-creating its counters on
restart); a different type under the same name still raises.
"""
from __future__ import annotations

import os
import threading

_lock = threading.Lock()
_registry: dict[str, "_Metric"] = {}

# snapshots carry the producing process so cross-worker aggregation can
# dedup a process reachable via two collection paths (pids collide
# across hosts; (node, pid) does not)
_NODE = os.uname().nodename


class _Metric:
    def __new__(cls, name: str, *args, **kwargs):
        if not name or not isinstance(name, str) or \
                any(c in name for c in " \t\n"):
            raise ValueError(f"bad metric name {name!r}")
        # check-and-register under ONE lock hold: two threads creating
        # the same name concurrently must converge on one instance (a
        # split check/insert would let the loser shadow the winner in
        # the registry — the silent value-drop bug all over again, just
        # behind a race window). registry_snapshot() skips entries whose
        # __init__ hasn't finished (_registered).
        with _lock:
            existing = _registry.get(name)
            if existing is not None:
                if type(existing) is not cls:
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different type")
                # same name + same type: hand back the LIVE instance
                # instead of shadowing it (which dropped all accumulated
                # values); __init__ sees _registered and merges
                return existing
            self = super().__new__(cls)
            _registry[name] = self
            return self

    def __init__(self, name: str, description: str = "",
                 tag_keys: tuple = ()):
        if getattr(self, "_registered", False):
            # re-instantiation of the registered instance: keep values,
            # adopt a description if we never had one
            if description and not self.description:
                self.description = description
            return
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._values: dict[tuple, float] = {}
        self._registered = True

    def set_default_tags(self, tags: dict):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: dict | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        return tuple(sorted(merged.items()))

    def snapshot(self) -> dict:
        with _lock:
            return {
                "name": self.name,
                "type": type(self).__name__,
                "description": self.description,
                "pid": os.getpid(),
                "node": _NODE,
                "values": [{"tags": dict(k), "value": v}
                           for k, v in self._values.items()],
            }


class Counter(_Metric):
    def inc(self, value: float = 1.0, tags: dict | None = None):
        if value < 0:
            raise ValueError("counters only increase")
        key = self._key(tags)
        with _lock:
            self._values[key] = self._values.get(key, 0.0) + value


class Gauge(_Metric):
    def set(self, value: float, tags: dict | None = None):
        with _lock:
            self._values[self._key(tags)] = float(value)


class Histogram(_Metric):
    def __init__(self, name: str, description: str = "",
                 boundaries: list | None = None, tag_keys: tuple = ()):
        if getattr(self, "_registered", False):
            # returned-existing path: merge description, keep the live
            # boundaries/counts (changing bucket layout mid-flight would
            # corrupt the accumulated distribution)
            super().__init__(name, description, tag_keys)
            return
        # subclass storage BEFORE super().__init__: _registered (set
        # there, last) is what tells registry_snapshot() this object is
        # fully built and safe to snapshot
        self.boundaries = sorted(boundaries or
                                 [0.001, 0.01, 0.1, 1, 10, 100])
        self._counts: dict[tuple, list] = {}
        self._sums: dict[tuple, float] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: dict | None = None):
        key = self._key(tags)
        with _lock:
            counts = self._counts.setdefault(
                key, [0] * (len(self.boundaries) + 1))
            import bisect

            counts[bisect.bisect_left(self.boundaries, value)] += 1
            self._sums[key] = self._sums.get(key, 0.0) + value
            self._values[key] = self._sums[key]   # exported as _sum

    def snapshot(self) -> dict:
        base = super().snapshot()
        with _lock:
            base["boundaries"] = self.boundaries
            base["counts"] = [{"tags": dict(k), "counts": list(v)}
                              for k, v in self._counts.items()]
        return base


def registry_snapshot() -> list[dict]:
    with _lock:
        # entries registered in __new__ but still mid-__init__ are not
        # yet snapshot-safe; they appear in the next snapshot
        metrics = [m for m in _registry.values()
                   if getattr(m, "_registered", False)]
    return [m.snapshot() for m in metrics]


def aggregate_snapshots(snapshots: list[dict]) -> list[dict]:
    """Merge per-process registry snapshots into one family per metric
    name: Counter values and Histogram bucket counts/sums are SUMMED per
    tag set across processes; Gauges keep the last collected value per
    tag set. Snapshots from the same (node, pid, name) are deduped first
    — the driver process answers both the local registry read and its
    raylet's worker fan-out, and double-counting it would inflate sums."""
    merged: dict[str, dict] = {}
    order: list[str] = []
    seen: set[tuple] = set()
    for snap in snapshots:
        name = snap.get("name")
        if name is None:
            continue
        ident = (snap.get("node"), snap.get("pid"), name)
        if None not in ident:
            if ident in seen:
                continue
            seen.add(ident)
        out = merged.get(name)
        if out is None:
            out = merged[name] = {
                "name": name, "type": snap["type"],
                "description": snap.get("description", ""),
                "_vals": {},
            }
            order.append(name)
            if snap["type"] == "Histogram":
                out["boundaries"] = list(snap.get("boundaries", []))
                out["_counts"] = {}
        if snap["type"] != out["type"]:
            continue   # cross-process type clash: keep the first family
        if snap["type"] == "Histogram" and \
                out["boundaries"] != list(snap.get("boundaries", [])):
            # bucket-layout clash across processes: drop this process's
            # contribution ENTIRELY (sums and counts together) — summing
            # its _sum while excluding its buckets would publish a
            # family where _sum disagrees with _count/_bucket
            continue
        if not out["description"] and snap.get("description"):
            out["description"] = snap["description"]
        for row in snap.get("values", []):
            key = tuple(sorted(row["tags"].items()))
            if snap["type"] == "Gauge":
                out["_vals"][key] = row["value"]
            else:
                out["_vals"][key] = out["_vals"].get(key, 0.0) + row["value"]
        if snap["type"] == "Histogram":
            for row in snap.get("counts", []):
                key = tuple(sorted(row["tags"].items()))
                cur = out["_counts"].get(key)
                counts = list(row["counts"])
                if cur is None or len(cur) != len(counts):
                    out["_counts"][key] = counts
                else:
                    out["_counts"][key] = [a + b
                                           for a, b in zip(cur, counts)]
    result = []
    for name in order:
        out = merged[name]
        out["values"] = [{"tags": dict(k), "value": v}
                         for k, v in out.pop("_vals").items()]
        if out["type"] == "Histogram":
            out["counts"] = [{"tags": dict(k), "counts": v}
                             for k, v in out.pop("_counts").items()]
        result.append(out)
    return result


def _escape_label_value(value) -> str:
    """Prometheus text-format label escaping: backslash, double-quote and
    newline must be escaped inside label values."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label(tags: dict, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in tags.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text(snapshots: list[dict]) -> str:
    """Standard Prometheus text exposition of aggregated snapshots.
    Histograms emit the full family: cumulative _bucket{le=...}, _count,
    and _sum series."""
    lines = []
    for snap in snapshots:
        name = snap["name"]
        kind = {"Counter": "counter", "Gauge": "gauge",
                "Histogram": "histogram"}.get(snap["type"], "untyped")
        if snap.get("description"):
            lines.append(f"# HELP {name} {snap['description']}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            bounds = snap.get("boundaries", [])
            sums = {tuple(sorted(r["tags"].items())): r["value"]
                    for r in snap["values"]}
            for row in snap.get("counts", []):
                tags = row["tags"]
                counts = row["counts"]
                cum = 0
                for b, c in zip(bounds, counts):
                    cum += c
                    le = f'le="{b}"'
                    lines.append(f"{name}_bucket{_label(tags, le)} {cum}")
                cum += counts[len(bounds)] if len(counts) > len(bounds) else 0
                inf = 'le="+Inf"'
                lines.append(f"{name}_bucket{_label(tags, inf)} {cum}")
                lines.append(f"{name}_count{_label(tags)} {cum}")
                key = tuple(sorted(tags.items()))
                lines.append(f"{name}_sum{_label(tags)} "
                             f"{sums.get(key, 0.0)}")
        else:
            for row in snap["values"]:
                lines.append(f"{name}{_label(row['tags'])} {row['value']}")
    return "\n".join(lines) + "\n"
