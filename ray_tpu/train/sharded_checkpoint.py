"""Crash-consistent sharded checkpointing with world-elastic restore.

PR 19 sharded optimizer state O(model/world) per rank (train/ddp.py
``ZeroOptimizer``); this module shards the CHECKPOINT the same way and
makes it survive the failures the rest of the stack already does:

- **Per-rank shard writes.** Each rank persists only its ZeRO shard —
  its ``[lo, hi)`` slice of every packed param bucket plus the
  optimizer-state slots for that slice, keyed by the deterministic
  bucket plan (``parallel/sharding.plan_buckets`` /
  ``plan_shard_map``) — as one ``.npz`` written through the sanctioned
  temp-file → fsync → rename idiom (``_private/atomic_write.py``), with
  its sha256 recorded. Numpy's lazy npz member loading means restore
  touches only the members it needs: no rank ever materializes another
  rank's optimizer state.

- **Two-phase atomic commit.** Ranks ack shard durability over the
  existing collective plane (one small ``allgather_object``), then rank
  0 ALONE writes the generation's ``MANIFEST.json`` (world size,
  bucket-plan fingerprint, per-shard digests) with the same
  write-fsync-rename discipline. A generation without a manifest is by
  definition torn and invisible to restore — a crash anywhere before
  the manifest rename loses at most one uncommitted generation, never
  the ability to restore.

- **Corruption detection + fallback.** Restore verifies the plan
  fingerprint and every shard's digest (streaming, chunked — full
  files are never held in memory); a bad/torn generation is quarantined
  (renamed ``*.quarantined``, ``CHECKPOINT_QUARANTINED`` event naming
  the shard and reason) and restore falls back to the newest complete
  one. ``prune_generations`` never deletes the last verified-complete
  generation, whatever ``num_to_keep`` says.

- **World-elastic restore.** A gang restarting at a different world
  size re-slices the saved shards onto the new shard map by pure index
  math over the plan (``parallel/sharding.reslice_spans`` — the plan
  depends only on shapes/dtypes, so old and new layouts index the same
  packed element streams). ``CHECKPOINT_RESHARDED`` marks the event;
  the result is bit-exact against a fixed-world restore (pinned in
  tests/test_zz_sharded_ckpt.py).

- **Async snapshot.** ``save_sharded(..., asynchronous=True)`` (the
  ``RAY_TPU_CHECKPOINT_ASYNC`` default) serializes the shard on the
  caller thread (cheap memcpy — the state captured is the state at
  call time) and moves the disk write to a background thread; the
  two-phase commit runs when the caller harvests the returned
  :class:`PendingSnapshot` at its next deterministic collective point.
  Both halves stamp step anatomy (kind ``checkpoint``; the background
  write lands as hidden time, the snapshot + any harvest residue as
  exposed), so a checkpoint stall is attributed, not mysterious.

Chaos: every disk write consults the fault plane's disk primitives
(``torn_write:`` / ``corrupt_file:`` / ``kill_actor:`` against the
``ckpt`` tag — see ``_private/fault_injection.py``), so every failure
mode above is a seeded, reproducible test.
"""
from __future__ import annotations

import hashlib
import io
import json
import os
import shutil
import threading
import time

from ray_tpu._private import events as _events
from ray_tpu._private import telemetry as _tm

GEN_PREFIX = "gen_"
MANIFEST = "MANIFEST.json"
QUARANTINE_SUFFIX = ".quarantined"
_DIGEST_CHUNK = 1 << 20


class CheckpointError(RuntimeError):
    pass


def _get_config(name):
    from ray_tpu._private.config import get_config

    return get_config(name)


def default_root() -> str | None:
    """The sharded-checkpoint root: the training session's directory
    (plumbed by the trainer from ``RunConfig.storage_path``) when inside
    a train worker, else the ``RAY_TPU_CHECKPOINT_DIR`` config knob."""
    try:
        from ray_tpu.air import session as _session

        d = getattr(_session._get_session(), "checkpoint_dir", None)
        if d:
            return d
    except Exception:
        pass
    d = _get_config("checkpoint_dir")
    return d or None


def shard_filename(rank: int, world: int) -> str:
    return f"shard_{int(rank):05d}_of_{int(world):05d}.npz"


def generation_dir(root: str, step: int) -> str:
    return os.path.join(root, f"{GEN_PREFIX}{int(step):08d}")


def _gen_step(dirname: str) -> int | None:
    base = os.path.basename(dirname.rstrip(os.sep))
    if not base.startswith(GEN_PREFIX) or base.endswith(QUARANTINE_SUFFIX):
        return None
    try:
        return int(base[len(GEN_PREFIX):])
    except ValueError:
        return None


def _list_generations(root: str) -> list:
    """[(step, path)] for live (non-quarantined) generations, newest
    first."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        path = os.path.join(root, name)
        step = _gen_step(path)
        if step is not None and os.path.isdir(path):
            out.append((step, path))
    out.sort(reverse=True)
    return out


def _file_sha256(path: str) -> str:
    """Streaming digest — never holds the file (i.e. a whole shard of
    optimizer state) in memory at once."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_DIGEST_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _record_anatomy(start_m: float, end_m: float, blocking: bool, **meta):
    try:
        from ray_tpu.parallel import step_anatomy

        step_anatomy.record_activity("checkpoint", start_m, end_m,
                                     blocking=blocking, **meta)
    except Exception:
        pass


# ----------------------------------------------------------------- save


def _build_shard_payload(params, optimizer, bucket_bytes, world, rank,
                         step, extra):
    """This rank's shard as (npz bytes, manifest-facing meta). Param
    slices come from packing each bucket and cutting ``[lo, hi)``;
    optimizer slots come from ``ZeroOptimizer.shard_state_dict()`` —
    already O(model/world)."""
    import numpy as np

    from ray_tpu.parallel import sharding as _sh

    leaves, _ = _sh.flatten_tree(params)
    if optimizer is not None:
        optimizer._ensure_plan(leaves)
        plan = optimizer._plan
        shard_map = optimizer._shard_map
        fingerprint = optimizer.plan_fingerprint
        opt_state = optimizer.shard_state_dict()
        step = int(step if step is not None else opt_state["step"])
        slots = sorted({k for st in opt_state["buckets"] for k in st})
    else:
        if bucket_bytes is None:
            bucket_bytes = int(_get_config("train_grad_bucket_bytes"))
        plan = _sh.plan_buckets(leaves, bucket_bytes)
        shard_map = _sh.plan_shard_map(leaves, plan, world)
        fingerprint = _sh.plan_fingerprint(leaves, plan)
        opt_state = None
        step = int(step or 0)
        slots = []
    arrays = {}
    for b, indices in enumerate(plan):
        lo, hi = shard_map[b]["bounds"][rank]
        pflat = _sh.pack_bucket(leaves, indices)
        arrays[f"param_{b}"] = np.array(pflat[lo:hi])
        if opt_state is not None:
            for slot, arr in opt_state["buckets"][b].items():
                arrays[f"opt_{b}_{slot}"] = np.asarray(arr)
    meta = {"rank": int(rank), "world": int(world), "step": step,
            "plan_fingerprint": fingerprint, "buckets": len(plan),
            "slots": slots, "extra": extra if extra is not None else {}}
    arrays["meta"] = np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8).copy()
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue(), meta


class PendingSnapshot:
    """One in-flight sharded checkpoint save. ``result(timeout)`` joins
    the background shard write (if any), runs the two-phase commit over
    the collective plane, and returns::

        {"committed": bool, "path": generation dir, "step": int,
         "manifest": dict | None, "error": str | None}

    All ranks MUST harvest at the same point in their collective
    sequence (SPMD) — the commit's durability ack is an
    ``allgather_object`` on the training group."""

    def __init__(self, root, gen_dir, step, world, rank, group_name,
                 keep, data, meta, asynchronous):
        self._root = root
        self._gen = gen_dir
        self._step = step
        self._world = world
        self._rank = rank
        self._group = group_name
        self._keep = keep
        self._data = data
        self._meta = meta
        self._write_error: str | None = None
        self._digest: str | None = None
        self._nbytes = len(data)
        self._result: dict | None = None
        self._thread: threading.Thread | None = None
        if asynchronous:
            self._thread = threading.Thread(
                target=self._write, name="rtpu-ckpt-write", daemon=True)
            self._thread.start()
        else:
            self._write()

    # ------------------------------------------------------------ write
    def _write(self):
        from ray_tpu._private.atomic_write import atomic_write

        path = os.path.join(self._gen, shard_filename(self._rank,
                                                      self._world))
        t0 = time.monotonic()
        background = self._thread is not None
        try:
            os.makedirs(self._gen, exist_ok=True)
            # digest the bytes we INTENDED to persist, not a re-read of
            # the file: a latent flip between write and read-back (the
            # corrupt_file fault) must make restore's digest check FAIL,
            # which only works if the manifest carries the clean hash
            self._digest = hashlib.sha256(self._data).hexdigest()
            atomic_write(path, self._data, tag="ckpt", name="shard")
            if _tm.ENABLED:
                _tm.observe("ray_tpu_checkpoint_write_seconds",
                            time.monotonic() - t0,
                            tags={"group": self._group or "local"})
                _tm.observe("ray_tpu_checkpoint_bytes",
                            float(self._nbytes),
                            tags={"group": self._group or "local"})
        except BaseException as e:
            self._write_error = f"{type(e).__name__}: {e}"
        finally:
            self._data = b""
            _record_anatomy(t0, time.monotonic(), blocking=not background,
                            phase="write", step=self._step)

    def done_writing(self) -> bool:
        return self._thread is None or not self._thread.is_alive()

    def _allgather_acks(self, ack):
        from ray_tpu.util import collective as col

        return col.allgather_object(ack, self._group)

    def _scan_acks(self, own_ack):
        acks = [own_ack]
        for r in range(self._world):
            if r == self._rank:
                continue
            path = os.path.join(self._gen, shard_filename(r, self._world))
            try:
                acks.append((r, _file_sha256(path),
                             os.path.getsize(path), None))
            except OSError as e:
                acks.append((r, None, 0,
                             f"shard not on disk: {type(e).__name__}"))
        return acks

    # ----------------------------------------------------------- commit
    def result(self, timeout: float | None = None) -> dict:
        if self._result is not None:
            return self._result
        if self._thread is not None:
            t0 = time.monotonic()
            self._thread.join(timeout)
            if self._thread.is_alive():
                raise TimeoutError(
                    f"sharded checkpoint shard write still in flight "
                    f"after {timeout}s ({self._gen})")
            t1 = time.monotonic()
            if t1 - t0 > 1e-4:
                # the residue the overlap window failed to hide
                _record_anatomy(t0, t1, blocking=True, phase="wait",
                                step=self._step)
        ack = (self._rank, self._digest, self._nbytes, self._write_error)
        if self._world > 1 and self._group:
            acks = self._allgather_acks(ack)
        elif self._world > 1:
            # groupless multi-rank save (driver-assembled gangs, unit
            # tests): the durability ack degrades to a directory scan —
            # rank 0's result() must run after every rank's write
            acks = self._scan_acks(ack)
        else:
            acks = [ack]
        acks = sorted(acks)
        errors = {r: err for r, _, _, err in acks if err}
        manifest = None
        if not errors and self._rank == 0:
            manifest = {
                "step": self._step, "world": self._world,
                "plan_fingerprint": self._meta["plan_fingerprint"],
                "buckets": self._meta["buckets"],
                "slots": self._meta["slots"],
                "shards": {str(r): {"file": shard_filename(r, self._world),
                                    "sha256": digest, "bytes": n}
                           for r, digest, n, _ in acks},
            }
            from ray_tpu._private.atomic_write import atomic_write

            try:
                atomic_write(os.path.join(self._gen, MANIFEST),
                             json.dumps(manifest, indent=1).encode(),
                             tag="ckpt", name="manifest")
            except BaseException as e:
                errors[0] = f"{type(e).__name__}: {e}"
                manifest = None
        if not errors:
            if self._rank == 0:
                _events.record("CHECKPOINT_COMMITTED", step=self._step,
                               world=self._world, path=self._gen,
                               shard_bytes=sum(n for _, _, n, _ in acks))
                if self._keep:
                    prune_generations(self._root, self._keep)
            self._result = {"committed": True, "path": self._gen,
                            "step": self._step, "manifest": manifest,
                            "error": None}
        else:
            # torn by definition: no manifest was (or ever will be)
            # written for this generation — restore cannot see it
            err = "; ".join(f"rank {r}: {m}" for r, m in
                            sorted(errors.items()))
            self._result = {"committed": False, "path": self._gen,
                            "step": self._step, "manifest": None,
                            "error": err}
        return self._result


def save_sharded(params, optimizer=None, *, root: str | None = None,
                 step: int | None = None, group_name: str | None = None,
                 world: int | None = None, rank: int | None = None,
                 bucket_bytes: int | None = None, extra: dict | None = None,
                 asynchronous: bool | None = None,
                 keep: int | None = None) -> PendingSnapshot:
    """Cut one sharded checkpoint generation; returns a
    :class:`PendingSnapshot` (already written in sync mode — harvest
    ``result()`` either way for the commit verdict).

    ``params`` is the full (replicated) param pytree; ``optimizer`` a
    ``train.ddp.ZeroOptimizer`` whose shard state rides along (step
    counter included). Without an optimizer the same sharded layout
    persists params only. ``world``/``rank`` default to the
    optimizer's gang (or 1/0 standalone); ``extra`` is a small
    JSON-able user dict riding every shard's meta."""
    if optimizer is not None:
        from ray_tpu.parallel import sharding as _sh

        leaves, _ = _sh.flatten_tree(params)
        optimizer._ensure_plan(leaves)
        world = optimizer._world if world is None else world
        rank = optimizer._rank if rank is None else rank
        group_name = group_name or optimizer._group
    if world is None and group_name:
        from ray_tpu.util import collective as col

        world = col.get_collective_group_size(group_name)
        rank = col.get_rank(group_name) if rank is None else rank
    world = 1 if world is None else int(world)
    rank = 0 if rank is None else int(rank)
    root = root or default_root()
    if not root:
        raise CheckpointError(
            "save_sharded: no checkpoint root — pass root=, set "
            "RAY_TPU_CHECKPOINT_DIR, or run under a trainer with a "
            "storage_path")
    if asynchronous is None:
        asynchronous = bool(_get_config("checkpoint_async"))
    t0 = time.monotonic()
    data, meta = _build_shard_payload(params, optimizer, bucket_bytes,
                                     world, rank, step, extra)
    _record_anatomy(t0, time.monotonic(), blocking=True, phase="snapshot",
                    step=meta["step"])
    gen = generation_dir(root, meta["step"])
    return PendingSnapshot(root, gen, meta["step"], world, rank,
                           group_name, keep, data, meta, asynchronous)


# -------------------------------------------------------------- verify


def _load_manifest(gen_dir: str) -> dict | None:
    try:
        with open(os.path.join(gen_dir, MANIFEST), "rb") as f:
            return json.loads(f.read().decode())
    except (OSError, ValueError):
        return None


def verify_generation(gen_dir: str, fingerprint: str | None = None,
                      digests: bool = True) -> dict:
    """Pure (no renames, no events) verification of one generation.
    Returns ``{"ok": bool, "reason": str|None, "shard": str|None,
    "manifest": dict|None}`` — reason is one of ``torn`` (no/unreadable
    manifest), ``plan_mismatch``, ``shard_missing``,
    ``digest_mismatch``, ``size_mismatch``."""
    manifest = _load_manifest(gen_dir)
    if manifest is None:
        return {"ok": False, "reason": "torn", "shard": None,
                "manifest": None}
    if fingerprint is not None and \
            manifest.get("plan_fingerprint") != fingerprint:
        return {"ok": False, "reason": "plan_mismatch", "shard": None,
                "manifest": manifest}
    for r in sorted(manifest.get("shards", {}), key=int):
        spec = manifest["shards"][r]
        path = os.path.join(gen_dir, spec["file"])
        if not os.path.isfile(path):
            return {"ok": False, "reason": "shard_missing",
                    "shard": spec["file"], "manifest": manifest}
        if os.path.getsize(path) != int(spec["bytes"]):
            return {"ok": False, "reason": "size_mismatch",
                    "shard": spec["file"], "manifest": manifest}
        if digests and _file_sha256(path) != spec["sha256"]:
            return {"ok": False, "reason": "digest_mismatch",
                    "shard": spec["file"], "manifest": manifest}
    return {"ok": True, "reason": None, "shard": None,
            "manifest": manifest}


def _quarantine(gen_dir: str, verdict: dict):
    """Rename a bad/torn generation out of restore's sight + record the
    event naming the shard and reason. Rename, not delete: the wreckage
    is evidence (the flight recorder / conftest failure hint point
    operators at it)."""
    from ray_tpu._private.atomic_write import fsync_dir

    target = gen_dir + QUARANTINE_SUFFIX
    try:
        os.rename(gen_dir, target)
        fsync_dir(os.path.dirname(gen_dir) or ".")
    except OSError:
        # every rank restores concurrently and each may see the same
        # torn generation: the losers' rename fails ENOENT because a
        # peer already moved it — the wreckage IS quarantined, do not
        # touch the target. Only when the source still exists (a
        # re-torn generation of the same step colliding with older
        # wreckage) replace the stale target and retry.
        if os.path.isdir(gen_dir):
            shutil.rmtree(target, ignore_errors=True)
            try:
                os.rename(gen_dir, target)
                fsync_dir(os.path.dirname(gen_dir) or ".")
            except OSError:
                target = gen_dir     # couldn't rename: record + skip
    _events.record("CHECKPOINT_QUARANTINED", path=gen_dir,
                   reason=verdict["reason"], shard=verdict["shard"])
    if _tm.ENABLED:
        _tm.counter_inc("ray_tpu_checkpoint_quarantined_total",
                        tags={"reason": verdict["reason"]})
    return target


# ------------------------------------------------------------- restore


def restore_sharded(params_template, optimizer=None, *,
                    root: str | None = None,
                    group_name: str | None = None,
                    world: int | None = None, rank: int | None = None,
                    bucket_bytes: int | None = None,
                    quarantine: bool = True):
    """Restore from the newest verified-complete generation under
    ``root``, re-slicing saved shards onto THIS world size when it
    differs from the saved one (pure index math — bit-exact vs a
    fixed-world restore). Bad/torn generations encountered on the way
    are quarantined (``CHECKPOINT_QUARANTINED``) and restore falls back
    to the next older one.

    Returns ``(params, meta)`` — ``params`` shaped like
    ``params_template``, ``meta`` with ``step`` / ``extra`` /
    ``world_saved`` / ``resharded`` / ``path`` — or ``None`` when no
    restorable generation exists. When ``optimizer`` is given, its
    shard state (this rank's slices only) and step counter are
    installed."""
    import numpy as np

    from ray_tpu.parallel import sharding as _sh

    if optimizer is not None and world is None:
        # the optimizer may not have a plan yet on a fresh gang; its
        # group still names the world
        group_name = group_name or optimizer._group
    if world is None:
        if group_name:
            from ray_tpu.util import collective as col

            world = col.get_collective_group_size(group_name)
            rank = col.get_rank(group_name) if rank is None else rank
        else:
            world = 1
    world = int(world)
    rank = 0 if rank is None else int(rank)
    root = root or default_root()
    if not root or not os.path.isdir(root):
        return None
    t_restore = time.monotonic()
    leaves, treedef = _sh.flatten_tree(params_template)
    if bucket_bytes is None:
        bucket_bytes = (optimizer._bucket_bytes
                        if optimizer is not None else None)
    if bucket_bytes is None:
        bucket_bytes = int(_get_config("train_grad_bucket_bytes"))
    plan = _sh.plan_buckets(leaves, bucket_bytes)
    shard_map = _sh.plan_shard_map(leaves, plan, world)
    fingerprint = _sh.plan_fingerprint(leaves, plan)
    chosen = None
    for step, gen_dir in _list_generations(root):
        verdict = verify_generation(gen_dir, fingerprint)
        if verdict["ok"]:
            chosen = (step, gen_dir, verdict["manifest"])
            break
        if quarantine:
            _quarantine(gen_dir, verdict)
    if chosen is None:
        return None
    step, gen_dir, manifest = chosen
    old_world = int(manifest["world"])
    slots = list(manifest.get("slots", ()))
    resharded = old_world != world

    payloads: dict[int, object] = {}   # old rank -> lazy npz handle

    def _payload(r: int):
        z = payloads.get(r)
        if z is None:
            z = np.load(os.path.join(
                gen_dir, manifest["shards"][str(r)]["file"]))
            payloads[r] = z
        return z

    out_leaves: list = [None] * len(leaves)
    opt_buckets: list = []
    try:
        for b, indices in enumerate(plan):
            elems = shard_map[b]["elems"]
            # full params on every rank: the rank-ordered concatenation
            # of the OLD layout's param slices IS the packed bucket
            flat = np.concatenate(
                [np.asarray(_payload(r)[f"param_{b}"])
                 for r in range(old_world)]) if old_world > 1 else \
                np.asarray(_payload(0)[f"param_{b}"])
            _sh.unpack_bucket(flat, leaves, indices, out_leaves)
            # optimizer state: ONLY this rank's [lo, hi) — assembled
            # from the overlapping spans of the old layout, touching
            # only those old shards' slot members (lazy npz access)
            if optimizer is not None and slots is not None:
                spans = _sh.reslice_spans(elems, old_world, world, rank)
                st = {}
                for slot in slots:
                    parts = [np.asarray(_payload(r)[f"opt_{b}_{slot}"]
                                        [lo:hi]) for r, lo, hi in spans]
                    st[slot] = (np.concatenate(parts) if len(parts) != 1
                                else np.array(parts[0]))
                opt_buckets.append(st)
    finally:
        for z in payloads.values():
            try:
                z.close()
            except Exception:
                pass
    for i, leaf in enumerate(leaves):
        if out_leaves[i] is None:
            out_leaves[i] = leaf
    params = _sh.unflatten_tree(treedef, out_leaves)
    if optimizer is not None:
        optimizer.load_shard_state_dict({
            "step": int(manifest["step"]),
            "plan_fingerprint": manifest["plan_fingerprint"],
            "buckets": opt_buckets})
    meta0 = _shard_meta(_payload_path(gen_dir, manifest, 0))
    if resharded:
        _events.record("CHECKPOINT_RESHARDED", path=gen_dir,
                       step=step, world_saved=old_world, world_now=world)
    if _tm.ENABLED:
        _tm.observe("ray_tpu_checkpoint_restore_seconds",
                    time.monotonic() - t_restore,
                    tags={"group": group_name or "local"})
    return params, {"step": int(manifest["step"]), "path": gen_dir,
                    "world_saved": old_world, "resharded": resharded,
                    "extra": (meta0 or {}).get("extra", {})}


def _payload_path(gen_dir: str, manifest: dict, rank: int) -> str:
    return os.path.join(gen_dir, manifest["shards"][str(rank)]["file"])


def _shard_meta(path: str) -> dict | None:
    import numpy as np

    try:
        with np.load(path) as z:
            return json.loads(bytes(z["meta"]).decode())
    except Exception:
        return None


# ------------------------------------------------------------- pruning


def prune_generations(root: str, keep: int) -> list:
    """Bound the on-disk generation count: keep the newest ``keep``
    COMMITTED generations, plus — unconditionally — the newest
    generation that verifies complete (manifest + every shard present
    at its manifested size; the cheap check, digests are restore's
    job). Torn generations older than the newest committed one are dead
    by definition and removed; quarantined wreckage is removed once it
    falls behind the kept window. Returns the removed paths."""
    keep = max(1, int(keep))
    gens = _list_generations(root)               # newest first
    committed = [(s, p) for s, p in gens
                 if _load_manifest(p) is not None]
    keep_paths = {p for _, p in committed[:keep]}
    for s, p in committed:
        if verify_generation(p, digests=False)["ok"]:
            keep_paths.add(p)                    # last verified-complete
            break
    newest_committed = committed[0][0] if committed else None
    removed = []
    for s, p in gens:
        if p in keep_paths:
            continue
        if _load_manifest(p) is None and (newest_committed is None
                                          or s >= newest_committed):
            continue    # possibly an in-flight save: not ours to judge
        shutil.rmtree(p, ignore_errors=True)
        removed.append(p)
    # quarantined wreckage: bounded the same way — drop any that is
    # older than the oldest generation we kept
    oldest_kept = min((_gen_step(p) for p in keep_paths
                       if _gen_step(p) is not None), default=None)
    try:
        names = os.listdir(root)
    except OSError:
        names = []
    for name in names:
        if not name.endswith(QUARANTINE_SUFFIX):
            continue
        step = _gen_step(os.path.join(root,
                                      name[:-len(QUARANTINE_SUFFIX)]))
        if step is None or oldest_kept is None or step < oldest_kept:
            path = os.path.join(root, name)
            shutil.rmtree(path, ignore_errors=True)
            removed.append(path)
    return removed


# ------------------------------------------------------------- summary


def summarize_checkpoints(root: str, digests: bool = True) -> list:
    """Per-generation status under ``root``, newest first — the
    ``ray-tpu checkpoints`` CLI and the conftest chaos-failure hint.
    Each entry: ``{"step", "path", "status", "world", "shards",
    "bytes", "reason", "shard"}`` with status ``committed`` / ``torn``
    / ``corrupt`` / ``quarantined``."""
    out = []
    for step, gen_dir in _list_generations(root):
        verdict = verify_generation(gen_dir, digests=digests)
        manifest = verdict["manifest"]
        status = "committed" if verdict["ok"] else (
            "torn" if verdict["reason"] == "torn" else "corrupt")
        out.append({
            "step": step, "path": gen_dir, "status": status,
            "world": manifest["world"] if manifest else None,
            "shards": len(manifest["shards"]) if manifest else
            sum(1 for n in os.listdir(gen_dir)
                if n.startswith("shard_")),
            "bytes": sum(int(s["bytes"])
                         for s in manifest["shards"].values())
            if manifest else None,
            "reason": verdict["reason"], "shard": verdict["shard"],
        })
    try:
        names = os.listdir(root)
    except OSError:
        names = []
    for name in sorted(names, reverse=True):
        if name.endswith(QUARANTINE_SUFFIX):
            path = os.path.join(root, name)
            step = _gen_step(path[:-len(QUARANTINE_SUFFIX)])
            out.append({"step": step, "path": path,
                        "status": "quarantined", "world": None,
                        "shards": None, "bytes": None, "reason": None,
                        "shard": None})
    out.sort(key=lambda e: (e["step"] is None, -(e["step"] or 0)))
    return out
