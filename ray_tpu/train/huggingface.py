"""HuggingFace Transformers integration (reference:
python/ray/train/huggingface/transformers/ — `prepare_trainer` +
`RayTrainReportCallback` adapt an off-the-shelf `transformers.Trainer`
to run data-parallel inside the actor gang, with HF's own train loop
reporting through the session).

Usage::

    from ray_tpu.train.huggingface import (
        TransformersTrainer, prepare_trainer, RayTrainReportCallback)

    def trainer_init(config):
        args = TrainingArguments(..., use_cpu=True, report_to=[])
        return Trainer(model=model_fn(), args=args, train_dataset=ds)

    result = TransformersTrainer(
        trainer_init,
        scaling_config=ScalingConfig(num_workers=2)).fit()

The gang's torch.distributed (gloo) process group is initialized before
`trainer_init` runs, and the distributed env vars (RANK/WORLD_SIZE/...)
are exported first so `TrainingArguments` → accelerate detect the
pre-initialized group and wrap the model in DDP themselves.
"""
from __future__ import annotations

from ray_tpu.train.torch import TorchConfig, TorchTrainer


_cb_cls = None


def _report_callback_cls():
    """The TrainerCallback subclass, created lazily ONCE (transformers
    import is heavy and optional for everything else in ray_tpu.train)
    and cached so isinstance checks work."""
    global _cb_cls
    if _cb_cls is None:
        from transformers import TrainerCallback

        class _RayTrainReportCallback(TrainerCallback):
            def on_log(self, args, state, control, logs=None, **kwargs):
                from ray_tpu.air import session

                if logs and state.is_world_process_zero:
                    metrics = {k: v for k, v in logs.items()
                               if isinstance(v, (int, float))}
                    metrics["step"] = state.global_step
                    session.report(metrics)

            def on_save(self, args, state, control, **kwargs):
                # stream the just-written HF checkpoint dir through the
                # session so Result.checkpoint / RunConfig.storage_path
                # fault tolerance work for HF runs (reference:
                # RayTrainReportCallback.on_save)
                from ray_tpu.air import session
                from ray_tpu.air.checkpoint import Checkpoint

                if not state.is_world_process_zero:
                    return
                import os

                path = os.path.join(
                    args.output_dir, f"checkpoint-{state.global_step}")
                if os.path.isdir(path):
                    session.report(
                        {"step": state.global_step, "saved": True},
                        checkpoint=Checkpoint.from_directory(path))

        _cb_cls = _RayTrainReportCallback
    return _cb_cls


def RayTrainReportCallback():
    """Factory for the report callback (reference:
    transformers.RayTrainReportCallback). A factory rather than a class:
    the TrainerCallback base can only be imported lazily. To customize
    reporting, add your own TrainerCallback alongside it."""
    return _report_callback_cls()()


def prepare_trainer(trainer):
    """Final fit-up of a user-constructed `transformers.Trainer` for the
    gang: attaches the report callback if absent (reference:
    transformers.prepare_trainer)."""
    cls = _report_callback_cls()
    if not any(isinstance(cb, cls)
               for cb in trainer.callback_handler.callbacks):
        trainer.add_callback(RayTrainReportCallback())
    return trainer


def _export_dist_env(local_rank: int):
    """accelerate/TrainingArguments read the torchrun-style env vars at
    TrainingArguments CONSTRUCTION; the gang initializes the process
    group directly, so mirror its coordinates into the env before user
    code builds the arguments. `local_rank` comes from the session (NOT
    dist.get_rank(): on multi-host gangs the global rank is wrong for
    per-host local-main gating like main_process_first caches)."""
    import os

    import torch.distributed as dist

    if dist.is_initialized():
        os.environ.setdefault("RANK", str(dist.get_rank()))
        os.environ.setdefault("WORLD_SIZE", str(dist.get_world_size()))
        os.environ.setdefault("LOCAL_RANK", str(local_rank))
        os.environ.setdefault("MASTER_ADDR", "127.0.0.1")
        os.environ.setdefault("MASTER_PORT", "29500")
        os.environ.setdefault("ACCELERATE_USE_CPU", "true")


class TransformersTrainer(TorchTrainer):
    """Run a `transformers.Trainer` per gang worker (reference:
    train/huggingface/transformers/transformers_trainer.py).

    ``trainer_init_per_worker(config) -> transformers.Trainer`` runs on
    every worker AFTER the torch.distributed group is up; HF/accelerate
    pick the group up and data-parallelize. The returned metrics come
    from the last session report (HF logs via RayTrainReportCallback).
    """

    def __init__(self, trainer_init_per_worker, *,
                 torch_config: TorchConfig | None = None, **kwargs):
        def train_loop(config):
            from ray_tpu.air import session

            _export_dist_env(session.get_local_rank())
            trainer = trainer_init_per_worker(config)
            trainer = prepare_trainer(trainer)
            out = trainer.train()
            final = {"training_loss":
                     float(getattr(out, "training_loss", 0.0)),
                     "global_step":
                     int(trainer.state.global_step),
                     "done": True}
            session.report(final)

        super().__init__(train_loop, torch_config=torch_config, **kwargs)
