"""TorchTrainer — torch.distributed data parallelism over the actor gang.

Reference: python/ray/train/torch/config.py:29,69,123 (_TorchBackend picks
a TCP rendezvous on rank 0 and calls dist.init_process_group on every
worker) and torch/torch_trainer.py. On this framework the TPU path is
JaxTrainer; TorchTrainer serves CPU-side torch workloads and migration
parity — same WorkerGroup/PG gang, gloo process group (NCCL absent by
design: GPU collectives are out of scope for a TPU-native build).
"""
from __future__ import annotations

from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.backend_executor import Backend
from ray_tpu.train.trainer import DataParallelTrainer


class TorchConfig:
    """(reference: train/torch/config.py TorchConfig)"""

    def __init__(self, backend: str = "gloo", init_timeout_s: float = 120.0):
        self.backend = backend
        self.init_timeout_s = init_timeout_s

    def backend_cls(self):
        return _TorchBackend(self)


class _TorchBackend(Backend):
    def __init__(self, config: TorchConfig):
        self.config = config

    def on_start(self, worker_group, scaling: ScalingConfig):
        # rank 0's host provides the TCP rendezvous (reference:
        # _setup_torch_process_group, train/torch/config.py:69)
        addr = worker_group.execute_single(0, "free_coordinator_address")
        backend = self.config.backend
        timeout_s = self.config.init_timeout_s

        def _setup(rank, world_size, addr, backend, timeout_s):
            import datetime

            import torch.distributed as dist

            if not dist.is_initialized():
                dist.init_process_group(
                    backend, init_method=f"tcp://{addr}",
                    rank=rank, world_size=world_size,
                    timeout=datetime.timedelta(seconds=timeout_s))
            return rank

        worker_group.execute(
            "run_setup", (_setup, (addr, backend, timeout_s), {}))

    def on_shutdown(self, worker_group):
        def _teardown(rank, world_size):
            import torch.distributed as dist

            if dist.is_initialized():
                dist.destroy_process_group()
            return True

        try:
            worker_group.execute("run_setup", (_teardown, (), {}))
        except Exception:
            pass


class TorchTrainer(DataParallelTrainer):
    """(reference: train/torch/torch_trainer.py TorchTrainer)"""

    def __init__(self, train_loop_per_worker, *,
                 torch_config: TorchConfig | None = None, **kwargs):
        super().__init__(train_loop_per_worker,
                         backend_config=torch_config or TorchConfig(),
                         **kwargs)


def prepare_model(model):
    """Wrap a torch model for data-parallel training (reference:
    train/torch/train_loop_utils.py prepare_model — DDP wrap; device
    placement is a no-op on CPU workers)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model
