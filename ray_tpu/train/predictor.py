"""Predictors — checkpoint → batch inference callable.

Reference: python/ray/train/predictor.py (Predictor.from_checkpoint /
predict over numpy|pandas batches) and train/batch_predictor.py
(BatchPredictor.predict maps a predictor over a Dataset on an actor
pool). The TPU-shaped default is JaxPredictor: params restored from an
AIR Checkpoint, a jitted apply function, numpy-in/numpy-out batches
(device transfer inside the compiled call).
"""
from __future__ import annotations

import numpy as np

from ray_tpu.air.checkpoint import Checkpoint


class Predictor:
    """Base predictor (reference: train/predictor.py:Predictor)."""

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kwargs) -> "Predictor":
        raise NotImplementedError

    def predict(self, batch):
        """batch: np.ndarray or {col: np.ndarray} → same-shaped output."""
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Runs a jitted apply_fn over restored params.

    ``apply_fn(params, batch_array) -> prediction_array``; checkpoints
    produced by ``session.report(checkpoint=Checkpoint.from_dict(...))``
    carry the params under ``params_key`` (default "params").
    """

    def __init__(self, params, apply_fn, jit: bool = True):
        import jax

        self.params = params
        self._apply = jax.jit(apply_fn) if jit else apply_fn

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *, apply_fn,
                        params_key: str = "params",
                        jit: bool = True) -> "JaxPredictor":
        data = checkpoint.to_dict()
        if params_key not in data:
            raise KeyError(
                f"checkpoint has no {params_key!r} entry "
                f"(keys: {sorted(data)})")
        return cls(data[params_key], apply_fn, jit=jit)

    def predict(self, batch):
        if isinstance(batch, dict):
            return {k: np.asarray(self._apply(self.params, v))
                    for k, v in batch.items()}
        return np.asarray(self._apply(self.params, batch))


# Per-process predictor cache: scoring-pool actors rebuild the predictor
# at most once per process even though every block task re-deserializes
# its closure (actor task args are serialized per call). Keyed by the
# OWNING BatchPredictor's unique id (not the checkpoint's) so two
# predictors sharing a checkpoint but differing in apply_fn/kwargs never
# collide; FIFO-bounded so old params don't pin process memory forever.
_PREDICTOR_CACHE: dict = {}
_PREDICTOR_CACHE_MAX = 4


def _cache_put(key, predictor):
    while len(_PREDICTOR_CACHE) >= _PREDICTOR_CACHE_MAX:
        _PREDICTOR_CACHE.pop(next(iter(_PREDICTOR_CACHE)))
    _PREDICTOR_CACHE[key] = predictor


class BatchPredictor:
    """Map a predictor over a Dataset on a pool of long-lived actors
    (reference: train/batch_predictor.py — each scoring actor builds the
    predictor once, then scores many blocks)."""

    def __init__(self, checkpoint: Checkpoint, predictor_cls,
                 **predictor_kwargs):
        self.checkpoint = checkpoint
        self.predictor_cls = predictor_cls
        self.predictor_kwargs = predictor_kwargs
        # content-addressed cache key: identical (checkpoint, class,
        # kwargs) reuse the cached predictor across jobs; differing
        # apply_fns/kwargs never collide (cloudpickle is content-based)
        try:
            import hashlib

            import cloudpickle

            blob = cloudpickle.dumps(
                (predictor_cls, sorted(predictor_kwargs.items())))
            self._cache_key = (checkpoint.id
                               + hashlib.sha1(blob).hexdigest()[:16])
        except Exception:
            import uuid

            self._cache_key = uuid.uuid4().hex

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, predictor_cls,
                        **kwargs) -> "BatchPredictor":
        return cls(checkpoint, predictor_cls, **kwargs)

    def predict(self, dataset, *, num_scoring_workers: int = 2,
                batch_format: str = "auto"):
        """Returns a materialized Dataset of predictions."""
        import ray_tpu
        from ray_tpu.data.dataset import ActorPoolStrategy

        # ship the checkpoint through the object store ONCE; block tasks
        # carry only the small ref, and each scoring process restores the
        # predictor a single time via the module-level cache
        ckpt_ref = ray_tpu.put(self.checkpoint)
        key = self._cache_key
        predictor_cls = self.predictor_cls
        kwargs = self.predictor_kwargs

        def score(batch):
            import ray_tpu
            from ray_tpu.train.predictor import _PREDICTOR_CACHE, _cache_put

            predictor = _PREDICTOR_CACHE.get(key)
            if predictor is None:
                ckpt = ray_tpu.get(ckpt_ref)
                predictor = predictor_cls.from_checkpoint(ckpt, **kwargs)
                _cache_put(key, predictor)
            return predictor.predict(batch)

        # Pin the checkpoint ref before deriving: in-flight block tasks
        # hold it only inside pickled closures, which the owner-based ref
        # counter can't see — dropping every pinned handle would free the
        # object out from under them. _pin propagates through
        # _with_stage/materialize, so chained .map(...) datasets keep the
        # checkpoint alive too.
        return dataset.map_batches(
            score, batch_format=batch_format,
        )._pin(ckpt_ref).materialize(
            compute=ActorPoolStrategy(num_scoring_workers))
