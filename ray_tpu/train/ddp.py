"""Bucketed data-parallel gradient synchronization over the host
collective plane.

Training on this framework's host-DP path was compute-then-communicate:
run the whole backward, then one synchronous allreduce over the whole
grad pytree — the wire idles during compute, the TPU idles during comm.
This module hides one under the other ("Exploring the limits of
Concurrency in ML Training on Google TPUs", arXiv:2011.03641; the same
shape as torch DDP's gradient buckets / Horovod tensor fusion):

- the grad pytree is flattened in jax's canonical deterministic order
  and partitioned into size-targeted buckets
  (``RAY_TPU_TRAIN_GRAD_BUCKET_BYTES``, ~4 MiB default; planning
  depends only on shapes/dtypes, so every rank derives byte-identical
  buckets — ``parallel/sharding.plan_buckets``);
- each bucket's allreduce launches **asynchronously**
  (``collective.allreduce_async`` → the group's background issue
  thread) as soon as the bucket is packed, so bucket k's comm overlaps
  the device→host fetch + packing of bucket k+1, the unpacking of
  completed buckets, and whatever compute the caller runs before
  ``result()`` — including the next microbatch's forward when used via
  ``sync_gradients_async``;
- ``result()`` waits all handles at the optimizer boundary, stamping
  each bucket's *actually blocked* time (the comm the backward failed
  to hide) into the metric + step-anatomy planes.

Composition: the quantized wire (PR 8) and the intra-host hierarchy
apply per bucket unchanged (each bucket is an ordinary float32-sum
allreduce); a poisoned gang (PR 5) fails every pending handle fast
with ``CollectiveGroupError``.

Determinism contract (pinned in tests/test_zz_bucket_ddp.py): all
ranks always return byte-identical synced grads (the ring/pair
exchange guarantees it per op). Bucketed-on vs the
``RAY_TPU_TRAIN_BUCKET_DDP=0`` kill switch (legacy single synchronous
allreduce over the whole flattened tree) is additionally
**bit-identical at world size 2** on the exact wire: the pairwise
exchange reduces every element as one two-operand IEEE add, which is
commutative, so bucket boundaries cannot change results. At larger
world sizes the ring's per-chunk reduction order depends on chunk
boundaries, so on-vs-off agree within float reassociation rounding
(the same caveat as the collective hierarchy) while staying exactly
rank-consistent either way.
"""
from __future__ import annotations

import time

from ray_tpu._private import memory_anatomy as _ma
from ray_tpu._private import profiling as _prof
from ray_tpu._private import telemetry as _tm


def _get_config(name):
    from ray_tpu._private.config import get_config

    return get_config(name)


class PendingGradSync:
    """In-flight bucketed gradient sync: every bucket's async allreduce
    has been launched; ``result(timeout)`` waits them in launch order,
    unpacks, and returns the synced grad pytree. Work the caller does
    between launch and ``result()`` overlaps ALL of the comm."""

    def __init__(self, group: str, treedef, leaves, launched,
                 world: int, average: bool, rank: int | None = None):
        self._group = group
        self._treedef = treedef
        self._leaves = leaves
        self._launched = launched    # [(indices, handle, t_launch)]
        self._world = world
        self._average = average
        self._rank = rank
        self._result = None
        self._out_leaves: list = [None] * len(leaves)
        self._next = 0               # harvest progress (retry-safe)

    @property
    def num_buckets(self) -> int:
        return len(self._launched)

    def poll(self) -> bool:
        """True once every bucket's allreduce completed."""
        return all(h.poll() for _, h, _ in self._launched)

    def result(self, timeout: float | None = None):
        """Wait every bucket at the optimizer boundary and return the
        synced pytree. Raises ``CollectiveGroupError`` if the gang was
        poisoned while buckets were in flight, ``TimeoutError`` on a
        wire stall (timeout-not-hang; default: the collective op
        timeout per bucket)."""
        if self._result is not None:
            return self._result
        from ray_tpu.parallel import sharding as _sh
        from ray_tpu.util import tracing as _tracing

        out_leaves = self._out_leaves
        tags = {"group": self._group}
        # resume from the first un-harvested bucket: a retry after a
        # failed/timed-out bucket must not re-observe the completed
        # buckets' wait/sync histograms (counts would exceed
        # buckets_total) nor re-unpack them
        while self._next < len(self._launched):
            b = self._next
            indices, handle, t_launch = self._launched[b]
            t0 = time.perf_counter()
            with _prof.record_span("train", f"grad_bucket_wait::{b}",
                                   {"group": self._group, "bucket": b}):
                with _tracing.span(f"grad_bucket_wait {b}", "INTERNAL",
                                   attributes={"group": self._group,
                                               "bucket": b}):
                    flat = handle.result(timeout)
            now = time.perf_counter()
            if _tm.ENABLED and self._rank is not None:
                # bucket landed: it is no longer in flight on the wire
                _ma.LEDGER.add_inflight(self._rank, -float(flat.nbytes))
            if _tm.ENABLED:
                _tm.observe("ray_tpu_train_bucket_wait_seconds",
                            now - t0, tags=tags)
                # launch→COMPLETION (the handle stamps done_at when the
                # op finishes on the issue thread) — NOT launch→harvest:
                # a caller that overlapped long compute before result()
                # must not inflate the bucket's apparent comm time (the
                # overlap-fraction panel divides wait by this)
                _tm.observe("ray_tpu_train_bucket_sync_seconds",
                            (handle.done_at or now) - t_launch,
                            tags=tags)
            if self._average:
                flat = flat / self._world
            _sh.unpack_bucket(flat, self._leaves, indices, out_leaves)
            self._next = b + 1
        self._result = _sh.unflatten_tree(self._treedef, out_leaves)
        # drop the launch-time references (packed buffers, raw grads)
        self._launched = []
        self._leaves = []
        return self._result


class _DoneSync:
    """Kill-switch / degenerate result: the sync already happened."""

    num_buckets = 0

    def __init__(self, result):
        self._result = result

    def poll(self) -> bool:
        return True

    def result(self, timeout: float | None = None):
        return self._result


class PendingShardSync:
    """In-flight sharded (ZeRO-style) gradient sync: every bucket's
    async reducescatter has been launched; each handle resolves to THIS
    rank's contiguous shard of the bucket's reduction. The shard map
    (``parallel/sharding.plan_shard_map``) is derived from shapes +
    dtypes only, so every rank agrees on who owns which ``[lo, hi)``
    slice of each packed bucket — the precondition for each rank to be
    the sole updater of its optimizer-state shard. ``wait_bucket(b)``
    harvests one bucket (the sharded optimizer's per-bucket hook);
    ``result()`` harvests all and returns the per-bucket shard list."""

    mode = "reducescatter"

    def __init__(self, group: str, treedef, leaves, plan, shard_map,
                 launched, world: int, average: bool,
                 rank: int | None = None):
        self._group = group
        self._treedef = treedef
        self._leaves = leaves
        self._plan = plan
        self._shard_map = shard_map
        self._launched = launched    # [(indices, handle, t_launch, nbytes)]
        self._world = world
        self._average = average
        self._rank = rank
        self._shards: list = [None] * len(launched)
        self._next = 0               # harvest progress (retry-safe)

    @property
    def num_buckets(self) -> int:
        return len(self._launched)

    @property
    def shard_map(self):
        return self._shard_map

    def poll(self) -> bool:
        return all(h.poll() for _, h, _, _ in self._launched)

    def _harvest_next(self, timeout: float | None):
        from ray_tpu.util import tracing as _tracing

        b = self._next
        indices, handle, t_launch, nbytes = self._launched[b]
        tags = {"group": self._group}
        t0 = time.perf_counter()
        with _prof.record_span("train", f"grad_bucket_wait::{b}",
                               {"group": self._group, "bucket": b}):
            with _tracing.span(f"grad_bucket_wait {b}", "INTERNAL",
                               attributes={"group": self._group,
                                           "bucket": b}):
                flat = handle.result(timeout)
        now = time.perf_counter()
        if _tm.ENABLED and self._rank is not None:
            _ma.LEDGER.add_inflight(self._rank, -float(nbytes))
        if _tm.ENABLED:
            _tm.observe("ray_tpu_train_bucket_wait_seconds",
                        now - t0, tags=tags)
            _tm.observe("ray_tpu_train_bucket_sync_seconds",
                        (handle.done_at or now) - t_launch, tags=tags)
        if self._average:
            flat = flat / self._world
        self._shards[b] = flat
        self._next = b + 1

    def wait_bucket(self, b: int, timeout: float | None = None):
        """This rank's reduced (or averaged) shard of bucket ``b``;
        harvests in launch order (handles complete FIFO on the issue
        thread, so waiting bucket b implies buckets < b are done)."""
        while self._next <= b:
            self._harvest_next(timeout)
        return self._shards[b]

    def result(self, timeout: float | None = None) -> list:
        """Harvest every bucket; returns the list of this rank's
        per-bucket shard arrays (use ``shard_map`` to locate them in
        the packed buckets)."""
        while self._next < len(self._launched):
            self._harvest_next(timeout)
        self._launched = []
        return self._shards


class _DoneShardSync:
    """Kill-switch / degenerate sharded result: the reducescatters
    already ran synchronously; same surface as PendingShardSync."""

    mode = "reducescatter"

    def __init__(self, shards, shard_map, plan):
        self._shards = shards
        self._shard_map = shard_map
        self._plan = plan

    @property
    def num_buckets(self) -> int:
        return len(self._shards)

    @property
    def shard_map(self):
        return self._shard_map

    def poll(self) -> bool:
        return True

    def wait_bucket(self, b: int, timeout: float | None = None):
        return self._shards[b]

    def result(self, timeout: float | None = None) -> list:
        return self._shards


def _resolve_mode(mode) -> str:
    m = mode if mode is not None else _get_config("train_ddp_mode")
    m = str(m).strip().lower()
    if m not in ("allreduce", "reducescatter"):
        raise ValueError(
            f"train DDP mode {mode!r}: expected 'allreduce' (legacy, "
            f"every rank gets the full synced tree) or 'reducescatter' "
            f"(ZeRO-style, each rank gets its shard of every bucket)")
    return m


def _sync_shards_async(grads, group_name: str, *, average: bool,
                       bucket_bytes: int | None, wire_dtype):
    """The ``mode="reducescatter"`` launch path: one async
    reducescatter per bucket, each handle yielding only this rank's
    shard — roughly half the wire bytes of an allreduce per bucket
    (each element crosses the wire once instead of reduce+broadcast).
    With ``RAY_TPU_TRAIN_BUCKET_DDP=0`` (or a backend without async
    support) the SAME bucket plan runs through synchronous
    reducescatters instead — the shard map must not change with the
    kill switch, or optimizer state sharded over it would be orphaned
    mid-run; only the overlap is given up."""
    from ray_tpu.parallel import sharding as _sh
    from ray_tpu.util import collective as col

    leaves, treedef = _sh.flatten_tree(grads)
    world = col.get_collective_group_size(group_name)
    if bucket_bytes is None:
        bucket_bytes = int(_get_config("train_grad_bucket_bytes"))
    plan = _sh.plan_buckets(leaves, bucket_bytes)
    shard_map = _sh.plan_shard_map(leaves, plan, world)
    rank = None
    tags = {"group": group_name}
    if _tm.ENABLED:
        try:
            rank = col.get_rank(group_name)
        except Exception:
            rank = None
        if rank is not None:
            _ma.LEDGER.note_train_state(
                "grads", rank, float(sum(l.nbytes for l in leaves)))
    wire_of = wire_dtype if callable(wire_dtype) else (
        lambda b, indices: wire_dtype)
    bucketed = bool(_get_config("train_bucket_ddp"))
    if not bucketed or not col.supports_async(group_name):
        shards = []
        for b, indices in enumerate(plan):
            flat = _sh.pack_bucket(leaves, indices)
            if _tm.ENABLED:
                _tm.observe("ray_tpu_train_bucket_bytes",
                            float(flat.nbytes), tags=tags)
                _tm.counter_inc("ray_tpu_train_buckets_total", tags=tags)
            shard = col.reducescatter(flat, group_name)
            if average:
                shard = shard / world
            shards.append(shard)
        return _DoneShardSync(shards, shard_map, plan)
    launched = []
    for b, indices in enumerate(plan):
        with _prof.record_span("train", f"grad_bucket_pack::{b}",
                               {"group": group_name, "bucket": b}):
            flat = _sh.pack_bucket(leaves, indices)
        if _tm.ENABLED:
            _tm.observe("ray_tpu_train_bucket_bytes", float(flat.nbytes),
                        tags=tags)
            _tm.counter_inc("ray_tpu_train_buckets_total", tags=tags)
            if rank is not None:
                _ma.LEDGER.add_inflight(rank, float(flat.nbytes))
        launched.append((indices,
                         col.reducescatter_async(
                             flat, group_name,
                             wire_dtype=wire_of(b, indices)),
                         time.perf_counter(), float(flat.nbytes)))
    return PendingShardSync(group_name, treedef, leaves, plan, shard_map,
                            launched, world, average, rank=rank)


def sync_gradients_async(grads, group_name: str = "train_dp", *,
                         average: bool = False,
                         bucket_bytes: int | None = None,
                         mode: str | None = None,
                         wire_dtype=None):
    """Launch the bucketed gradient sync and return a
    ``PendingGradSync`` immediately — overlap the comm with anything
    (the next microbatch's forward, metrics, logging), then call
    ``.result()`` at the optimizer boundary.

    ``mode`` (default: the ``RAY_TPU_TRAIN_DDP_MODE`` config knob,
    ``allreduce``) selects the sync shape: ``allreduce`` returns the
    full synced tree on every rank; ``reducescatter`` is the ZeRO-style
    sharded sync — the returned ``PendingShardSync`` yields only this
    rank's ``[lo, hi)`` shard of each packed bucket (see
    ``ZeroOptimizer`` for the sharded optimizer riding it).
    ``wire_dtype`` ("bf16"/"int8", or a ``(bucket, indices) -> fmt``
    callable for per-bucket opt-in) quantizes the reducescatter wire;
    it applies to the sharded mode only.

    With ``RAY_TPU_TRAIN_BUCKET_DDP=0`` the legacy path runs instead:
    one synchronous allreduce over the whole flattened tree (one op per
    dtype for mixed-dtype trees), completed before this returns — and
    the sharded mode degrades to synchronous per-bucket reducescatters
    over the unchanged shard map."""
    from ray_tpu.parallel import sharding as _sh
    from ray_tpu.util import collective as col

    mode = _resolve_mode(mode)
    if mode == "reducescatter":
        return _sync_shards_async(grads, group_name, average=average,
                                  bucket_bytes=bucket_bytes,
                                  wire_dtype=wire_dtype)
    if wire_dtype is not None:
        raise ValueError(
            "wire_dtype is a per-bucket opt-in on the reducescatter "
            "path; the allreduce mode composes with the group-wide "
            "RAY_TPU_COLLECTIVE_WIRE_DTYPE knob instead")
    leaves, treedef = _sh.flatten_tree(grads)
    world = col.get_collective_group_size(group_name)
    if not leaves or world == 1:
        # world-1 sum is the identity (and average divides by 1):
        # skip the pack/allreduce/unpack round entirely
        return _DoneSync(grads)
    bucketed = bool(_get_config("train_bucket_ddp"))
    if bucket_bytes is None:
        bucket_bytes = int(_get_config("train_grad_bucket_bytes"))
    if not bucketed or not col.supports_async(group_name):
        # legacy: the whole tree as ONE synchronous allreduce (one
        # per dtype — a bucket must be contiguous in one dtype), the
        # exact pre-bucketing semantics the kill switch promises.
        # Also the degrade path for backends without async support
        # (xla) — the sync allreduce works there, so a grad sync must
        # not fail where the kill-switch path would succeed
        plan = _sh.plan_buckets(leaves, 1 << 62)
        out_leaves: list = [None] * len(leaves)
        for indices in plan:
            flat = col.allreduce(_sh.pack_bucket(leaves, indices),
                                 group_name)
            if average:
                flat = flat / world
            _sh.unpack_bucket(flat, leaves, indices, out_leaves)
        return _DoneSync(_sh.unflatten_tree(treedef, out_leaves))
    plan = _sh.plan_buckets(leaves, bucket_bytes)
    launched = []
    tags = {"group": group_name}
    rank = None
    if _tm.ENABLED:
        try:
            rank = col.get_rank(group_name)
        except Exception:
            rank = None
        if rank is not None:
            # exact by construction: the flatten is deterministic, so
            # this is THE grads footprint the sync moves for this rank
            _ma.LEDGER.note_train_state(
                "grads", rank, float(sum(l.nbytes for l in leaves)))
    for b, indices in enumerate(plan):
        # pack on the caller thread: bucket b's device→host fetch +
        # memcpy runs while buckets < b are already on the wire
        with _prof.record_span("train", f"grad_bucket_pack::{b}",
                               {"group": group_name, "bucket": b}):
            flat = _sh.pack_bucket(leaves, indices)
        if _tm.ENABLED:
            _tm.observe("ray_tpu_train_bucket_bytes", float(flat.nbytes),
                        tags=tags)
            _tm.counter_inc("ray_tpu_train_buckets_total", tags=tags)
            if rank is not None:
                _ma.LEDGER.add_inflight(rank, float(flat.nbytes))
        launched.append((indices, col.allreduce_async(flat, group_name),
                         time.perf_counter()))
    return PendingGradSync(group_name, treedef, leaves, launched, world,
                           average, rank=rank)


def sync_gradients(grads, group_name: str = "train_dp", *,
                   average: bool = False,
                   bucket_bytes: int | None = None,
                   mode: str | None = None,
                   wire_dtype=None):
    """Synchronize one grad pytree across the data-parallel gang and
    return the summed (or averaged) grads — or, in
    ``mode="reducescatter"``, the list of this rank's per-bucket
    shards. Bucketed + async under the hood (see module docstring);
    the pack/unpack of neighboring buckets still overlaps each bucket's
    comm even though this call itself blocks until the sync is done."""
    # timeout=None = the collective op timeout per bucket (the wire's
    # failure detector of last resort) — bounded, never a silent hang
    return sync_gradients_async(
        grads, group_name, average=average, bucket_bytes=bucket_bytes,
        mode=mode, wire_dtype=wire_dtype).result(timeout=None)


# ------------------------------------------------- sharded optimizer (ZeRO)
#
# ZeRO-1/2-style sharded optimizer over the bucket plan: grads arrive
# per-bucket via reducescatter (each rank holds only its [lo, hi) shard
# of every bucket), the optimizer state for that shard lives ONLY on
# its owner rank (O(model/world) state per rank instead of O(model)),
# and updated param shards return via per-bucket ASYNC allgathers that
# ride the issue thread while later buckets are still applying — and
# while the caller runs the next step's work, because the gather
# handles are waited only at first use of the new params.
#
# The shard optimizers here are strictly ELEMENTWISE numpy updates
# (sgd/momentum/adam): applying them per-shard then allgathering is
# exactly the computation legacy mode runs on the full vector, element
# for element — so at world 2, where the pairwise exchange makes
# reducescatter's shard bit-identical to the allreduce result's same
# slice, the final params are bit-identical to legacy allreduce + full
# apply (pinned by test). Optimizers with cross-element coupling
# (global grad-norm clipping, LAMB trust ratios) would need an extra
# scalar sync per step and are deliberately out of scope.


class _SgdShard:
    """Elementwise SGD (+momentum) on one shard; state: momentum only."""

    name = "sgd"

    def __init__(self, lr: float, momentum: float = 0.0):
        self.lr = float(lr)
        self.momentum = float(momentum)
        self.slots = 1 if momentum else 0

    def init(self, nelems: int, dtype):
        import numpy as np

        if not self.momentum:
            return {}
        return {"m": np.zeros(nelems, dtype=dtype)}

    def apply(self, p, g, state, step: int):
        if self.momentum:
            m = state["m"]
            m *= self.momentum
            m += g
            p -= self.lr * m
        else:
            p -= self.lr * g
        return p


class _AdamShard:
    """Elementwise Adam on one shard; state: first + second moments."""

    name = "adam"
    slots = 2

    def __init__(self, lr: float, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8):
        self.lr = float(lr)
        self.b1 = float(b1)
        self.b2 = float(b2)
        self.eps = float(eps)

    def init(self, nelems: int, dtype):
        import numpy as np

        return {"m": np.zeros(nelems, dtype=dtype),
                "v": np.zeros(nelems, dtype=dtype)}

    def apply(self, p, g, state, step: int):
        import numpy as np

        m, v = state["m"], state["v"]
        m *= self.b1
        m += (1.0 - self.b1) * g
        v *= self.b2
        v += (1.0 - self.b2) * (g * g)
        mhat = m / (1.0 - self.b1 ** step)
        vhat = v / (1.0 - self.b2 ** step)
        p -= self.lr * mhat / (np.sqrt(vhat) + self.eps)
        return p


def zero_sgd(lr: float, momentum: float = 0.0) -> _SgdShard:
    """Shard optimizer for :class:`ZeroOptimizer`: elementwise SGD."""
    return _SgdShard(lr, momentum)


def zero_adam(lr: float, b1: float = 0.9, b2: float = 0.999,
              eps: float = 1e-8) -> _AdamShard:
    """Shard optimizer for :class:`ZeroOptimizer`: elementwise Adam."""
    return _AdamShard(lr, b1, b2, eps)


class PendingParams:
    """In-flight sharded apply: every bucket's updated param shard has
    an async allgather on the wire. ``result()`` waits the gathers at
    FIRST USE, reassembles each packed bucket from the per-rank shards,
    and unflattens the new params tree — so the gathers overlap
    whatever the caller runs between the optimizer step and the next
    forward (data loading, metrics, host→device transfer), and step
    anatomy attributes that comm as hidden."""

    def __init__(self, group: str, treedef, leaves, plan, shard_map,
                 gathers, rank: int | None):
        self._group = group
        self._treedef = treedef
        self._leaves = leaves
        self._plan = plan
        self._shard_map = shard_map
        self._gathers = gathers      # [(b, handle, t_launch, nbytes)]
        self._rank = rank
        self._result = None

    @property
    def num_buckets(self) -> int:
        return len(self._gathers)

    def poll(self) -> bool:
        return all(h.poll() for _, h, _, _ in self._gathers)

    def result(self, timeout: float | None = None):
        """The updated params pytree; blocks on any allgather still in
        flight (the residue the overlap window failed to hide)."""
        if self._result is not None:
            return self._result
        import numpy as np

        from ray_tpu.parallel import sharding as _sh

        tags = {"group": self._group}
        out_leaves: list = [None] * len(self._leaves)
        done = [None] * len(self._plan)
        for b, handle, t_launch, nbytes in self._gathers:
            t0 = time.perf_counter()
            parts = handle.result(timeout)
            now = time.perf_counter()
            if _tm.ENABLED:
                _tm.observe("ray_tpu_train_param_gather_wait_seconds",
                            now - t0, tags=tags)
                _tm.observe("ray_tpu_train_param_gather_seconds",
                            (handle.done_at or now) - t_launch,
                            tags=tags)
                if self._rank is not None:
                    _ma.LEDGER.add_inflight(self._rank, -float(nbytes))
            # shard bounds are contiguous in rank order, so the packed
            # bucket is exactly the rank-ordered concatenation
            done[b] = np.concatenate([np.asarray(p).reshape(-1)
                                      for p in parts])
        for b, indices in enumerate(self._plan):
            if done[b] is not None:
                _sh.unpack_bucket(done[b], self._leaves, indices,
                                  out_leaves)
        # leaves the plan never covered (empty tree edge) stay original
        for i, leaf in enumerate(self._leaves):
            if out_leaves[i] is None:
                out_leaves[i] = leaf
        self._result = _sh.unflatten_tree(self._treedef, out_leaves)
        self._gathers = []
        self._leaves = []
        return self._result


class ZeroOptimizer:
    """ZeRO-style sharded optimizer over the DDP bucket plan.

    Each rank owns the ``[lo, hi)`` shard of every packed bucket that
    the deterministic shard map (``parallel/sharding.plan_shard_map``,
    same divmod split as the collective backend's reducescatter)
    assigns it, materializes optimizer state for ONLY that shard, and
    updates only those elements each step — the O(model) replicated
    optimizer state of legacy DDP becomes O(model/world) per rank,
    proven live via the ``ray_tpu_train_state_bytes{kind=opt_state}``
    gauge this class stamps.

    Step pipeline (``step_async``): per bucket, fold the last
    microbatch's grads → launch ``reducescatter_async`` (bucket b's
    wire time hides under bucket b+1's pack), then harvest: wait shard
    b → elementwise apply on the shard → launch ``allgather_async`` of
    the updated param shard — the gather of bucket k rides the issue
    thread under the apply of bucket k+1, and the returned
    :class:`PendingParams` waits the gathers only at first use.
    ``accumulate(grads)`` is the grad-accumulation hook: earlier
    microbatches fold into host accumulators with no comm; the final
    microbatch goes straight to ``step_async`` so each bucket launches
    the moment its fold completes, not at the step boundary.

    ``state_budget_bytes`` (optional) is a hard per-rank cap: state
    materialization raises when this rank's shard state would exceed
    it — the acceptance harness trains models whose REPLICATED state
    breaks the budget that the sharded state fits.
    """

    def __init__(self, opt, group_name: str = "train_dp", *,
                 bucket_bytes: int | None = None, wire_dtype=None,
                 state_budget_bytes: int | None = None,
                 average: bool = False):
        self._opt = opt
        self._group = group_name
        self._bucket_bytes = bucket_bytes
        self._wire = wire_dtype
        self._budget = state_budget_bytes
        self._average = average
        self._plan = None
        self._shard_map = None
        self._sig = None             # (shape, dtype) leaf signature
        self._state: dict = {}       # bucket -> this rank's state dict
        self._acc: list | None = None
        self._step = 0
        self._world = None
        self._rank = None
        self._fingerprint = None     # sharding.plan_fingerprint of plan
        self._pending_state = None   # load_shard_state_dict before plan

    # ------------------------------------------------------------ plan
    def _ensure_plan(self, leaves):
        from ray_tpu.parallel import sharding as _sh
        from ray_tpu.util import collective as col

        sig = tuple((tuple(getattr(l, "shape", ())),
                     str(getattr(l, "dtype", "object"))) for l in leaves)
        if sig == self._sig:
            return
        if self._sig is not None:
            # structure changed mid-run: the shard map (and therefore
            # every rank's state slices) is stale — refuse to guess
            raise ValueError(
                "ZeroOptimizer: param/grad tree structure changed; the "
                "bucket shard map (and the optimizer state sharded "
                "over it) is derived from leaf shapes and cannot be "
                "remapped in place")
        bucket_bytes = self._bucket_bytes
        if bucket_bytes is None:
            bucket_bytes = int(_get_config("train_grad_bucket_bytes"))
        self._world = col.get_collective_group_size(self._group)
        self._rank = col.get_rank(self._group)
        self._plan = _sh.plan_buckets(leaves, bucket_bytes)
        self._shard_map = _sh.plan_shard_map(leaves, self._plan,
                                             self._world)
        self._sig = sig
        self._fingerprint = _sh.plan_fingerprint(leaves, self._plan)
        if self._pending_state is not None:
            self._install_pending_state()

    def _my_bounds(self, b: int):
        return self._shard_map[b]["bounds"][self._rank]

    # ----------------------------------------------------------- state
    def _shard_state(self, b: int) -> dict:
        st = self._state.get(b)
        if st is None:
            lo, hi = self._my_bounds(b)
            st = self._opt.init(hi - lo, self._shard_map[b]["dtype"])
            self._state[b] = st
            self._note_state()
        return st

    def _note_state(self):
        total = self.state_bytes()
        if self._budget is not None and total > self._budget:
            raise RuntimeError(
                f"ZeroOptimizer: this rank's optimizer-state shard "
                f"({int(total)} bytes) exceeds the per-rank budget "
                f"({int(self._budget)} bytes) — raise the budget, "
                f"grow the gang, or use a lighter optimizer")
        if _tm.ENABLED and self._rank is not None:
            _ma.LEDGER.note_train_state("opt_state", self._rank,
                                        float(total))

    def state_bytes(self) -> float:
        """Exact flatten-sum of this rank's materialized shard state —
        the number the opt_state gauge carries."""
        return float(sum(arr.nbytes for st in self._state.values()
                         for arr in st.values()))

    def replicated_state_bytes(self) -> float:
        """What ONE rank would hold if the state were replicated (the
        legacy-DDP footprint): slots × elements × itemsize over the
        whole plan. The world-fold claim is
        ``state_bytes() ≈ replicated_state_bytes() / world``."""
        if self._shard_map is None:
            raise ValueError("ZeroOptimizer: no plan yet (run a step "
                             "or accumulate first)")
        slots = int(getattr(self._opt, "slots", 0))
        return float(sum(e["elems"] * e["dtype"].itemsize * slots
                         for e in self._shard_map))

    @property
    def shard_map(self):
        return self._shard_map

    @property
    def step_count(self) -> int:
        return self._step

    @property
    def plan_fingerprint(self) -> str | None:
        """World-independent identity of the bucket plan (see
        ``parallel/sharding.plan_fingerprint``); ``None`` before the
        first step/accumulate establishes the plan."""
        return self._fingerprint

    # ------------------------------------------- sharded checkpoint I/O
    def shard_state_dict(self) -> dict:
        """This rank's optimizer-state shard for the sharded checkpoint
        plane (``train/sharded_checkpoint.py``): per-bucket slot arrays
        covering ONLY this rank's ``[lo, hi)`` of each packed bucket,
        plus the step counter (adam bias correction depends on it) and
        the plan fingerprint restore must verify. O(model/world) — full
        state never exists on any rank."""
        import numpy as np

        if self._plan is None:
            raise ValueError("ZeroOptimizer: no plan yet (run a step "
                             "or accumulate first)")
        buckets = []
        for b in range(len(self._plan)):
            st = self._shard_state(b)
            buckets.append({k: np.asarray(v) for k, v in st.items()})
        return {"step": self._step,
                "plan_fingerprint": self._fingerprint,
                "world": self._world, "rank": self._rank,
                "buckets": buckets}

    def load_shard_state_dict(self, state: dict):
        """Install a shard-state dict (from :meth:`shard_state_dict`,
        possibly re-sliced onto this world size by the sharded
        checkpoint plane). Before the first step the plan is unknown, so
        the state parks and installs when the plan is established —
        fingerprint and per-bucket lengths are verified then."""
        self._pending_state = dict(state)
        if self._plan is not None:
            self._install_pending_state()

    def _install_pending_state(self):
        pend, self._pending_state = self._pending_state, None
        fp = pend.get("plan_fingerprint")
        if fp is not None and self._fingerprint is not None \
                and fp != self._fingerprint:
            raise ValueError(
                f"ZeroOptimizer: checkpointed plan fingerprint "
                f"{fp[:12]}… does not match this model's "
                f"{self._fingerprint[:12]}… — the saved shards were cut "
                f"over a different leaf signature/bucket plan and "
                f"cannot be re-sliced onto it")
        buckets = pend.get("buckets", [])
        if len(buckets) != len(self._plan):
            raise ValueError(
                f"ZeroOptimizer: checkpoint has {len(buckets)} bucket "
                f"states, plan has {len(self._plan)} buckets")
        for b, st in enumerate(buckets):
            lo, hi = self._my_bounds(b)
            for slot, arr in st.items():
                if int(getattr(arr, "size", -1)) != hi - lo:
                    raise ValueError(
                        f"ZeroOptimizer: bucket {b} slot {slot!r} has "
                        f"{getattr(arr, 'size', None)} elements, this "
                        f"rank's shard is {hi - lo}")
            self._state[b] = dict(st)
        self._step = int(pend.get("step", 0))
        self._note_state()

    # ------------------------------------------------------------ step
    def accumulate(self, grads):
        """Grad-accumulation hook: fold one microbatch's grads into the
        host-side per-bucket accumulators (pack + add; no comm). Feed
        the FINAL microbatch to ``step_async(params, grads=...)``
        instead — its fold interleaves with the bucket launches."""
        from ray_tpu.parallel import sharding as _sh

        leaves, _ = _sh.flatten_tree(grads)
        self._ensure_plan(leaves)
        if self._acc is None:
            self._acc = [None] * len(self._plan)
        for b, indices in enumerate(self._plan):
            flat = _sh.pack_bucket(leaves, indices)
            if self._acc[b] is None:
                self._acc[b] = flat   # pack allocates: safe to own
            else:
                self._acc[b] += flat

    def step_async(self, params, grads=None,
                   timeout: float | None = None) -> PendingParams:
        """One sharded optimizer step. Folds ``grads`` (the last — or
        only — microbatch; optional when ``accumulate`` already folded
        everything), launches the per-bucket reducescatters as each
        bucket's fold completes, applies this rank's shards as they
        land (later buckets' wire time and earlier buckets' gathers
        hide under the apply), and returns a :class:`PendingParams`
        with the allgathers in flight."""
        import numpy as np

        from ray_tpu.parallel import sharding as _sh
        from ray_tpu.util import collective as col

        leaves, treedef = _sh.flatten_tree(params)
        self._ensure_plan(leaves)
        if grads is None and self._acc is None:
            raise ValueError("ZeroOptimizer.step_async: no grads — "
                             "pass grads= or call accumulate() first")
        gleaves = None
        if grads is not None:
            gleaves, _ = _sh.flatten_tree(grads)
        self._step += 1
        tags = {"group": self._group}
        rank = self._rank if _tm.ENABLED else None
        if rank is not None:
            _ma.LEDGER.note_train_state(
                "grads", rank,
                float(sum(l.nbytes for l in (gleaves or leaves))))
        wire_of = self._wire if callable(self._wire) else (
            lambda b, indices: self._wire)
        bucketed = (bool(_get_config("train_bucket_ddp"))
                    and col.supports_async(self._group))
        # launch: fold bucket b, put its reducescatter on the wire,
        # move on to folding b+1 — grads go out as they become final
        launched = []
        for b, indices in enumerate(self._plan):
            flat = None
            if gleaves is not None:
                with _prof.record_span(
                        "train", f"grad_bucket_pack::{b}",
                        {"group": self._group, "bucket": b}):
                    flat = _sh.pack_bucket(gleaves, indices)
                if self._acc is not None and self._acc[b] is not None:
                    flat += self._acc[b]
            else:
                flat = self._acc[b]
            if _tm.ENABLED:
                _tm.observe("ray_tpu_train_bucket_bytes",
                            float(flat.nbytes), tags=tags)
                _tm.counter_inc("ray_tpu_train_buckets_total", tags=tags)
            if bucketed:
                if rank is not None:
                    _ma.LEDGER.add_inflight(rank, float(flat.nbytes))
                launched.append(
                    (indices,
                     col.reducescatter_async(
                         flat, self._group,
                         wire_dtype=wire_of(b, indices)),
                     time.perf_counter(), float(flat.nbytes)))
            else:
                launched.append((indices, flat, None, None))
        self._acc = None
        # harvest: wait shard b, apply, launch its allgather — while
        # this rank runs the apply math, bucket b+1's reducescatter and
        # buckets < b's allgathers proceed on the issue thread
        gathers = []
        for b, (indices, h, t_launch, nbytes) in enumerate(launched):
            lo, hi = self._my_bounds(b)
            with _prof.record_span("train", f"param_shard_pack::{b}",
                                   {"group": self._group, "bucket": b}):
                pflat = _sh.pack_bucket(leaves, indices)
            pshard = np.array(pflat[lo:hi])  # own the slice memory
            if bucketed:
                t0 = time.perf_counter()
                gshard = h.result(timeout)
                now = time.perf_counter()
                if _tm.ENABLED:
                    _tm.observe("ray_tpu_train_bucket_wait_seconds",
                                now - t0, tags=tags)
                    _tm.observe("ray_tpu_train_bucket_sync_seconds",
                                (h.done_at or now) - t_launch, tags=tags)
                    if rank is not None:
                        _ma.LEDGER.add_inflight(rank, -float(nbytes))
            else:
                gshard = col.reducescatter(h, self._group)
            if self._average:
                gshard = gshard / self._world
            st = self._shard_state(b)
            with _prof.record_span("train", f"shard_apply::{b}",
                                   {"group": self._group, "bucket": b}):
                pshard = self._opt.apply(pshard, np.asarray(gshard), st,
                                         self._step)
            bucket_bytes_full = float(
                self._shard_map[b]["elems"]
                * self._shard_map[b]["dtype"].itemsize)
            if bucketed:
                if rank is not None:
                    _ma.LEDGER.add_inflight(rank, bucket_bytes_full)
                gathers.append((b, col.allgather_async(pshard,
                                                       self._group),
                                time.perf_counter(), bucket_bytes_full))
            else:
                parts = col.allgather(pshard, self._group)
                gathers.append((b, _DoneHandle(parts), time.perf_counter(),
                                0.0))
        return PendingParams(self._group, treedef, leaves, self._plan,
                             self._shard_map, gathers, rank)

    def step(self, params, grads=None, timeout: float | None = None):
        """Blocking convenience: ``step_async(...).result()``."""
        return self.step_async(params, grads, timeout).result(timeout)


class _DoneHandle:
    """Completed pseudo-handle for the kill-switch path: the op already
    ran synchronously; PendingParams treats it like a real handle."""

    done_at = None

    def __init__(self, value):
        self._value = value

    def poll(self) -> bool:
        return True

    def result(self, timeout: float | None = None):
        return self._value
