"""Bucketed data-parallel gradient synchronization over the host
collective plane.

Training on this framework's host-DP path was compute-then-communicate:
run the whole backward, then one synchronous allreduce over the whole
grad pytree — the wire idles during compute, the TPU idles during comm.
This module hides one under the other ("Exploring the limits of
Concurrency in ML Training on Google TPUs", arXiv:2011.03641; the same
shape as torch DDP's gradient buckets / Horovod tensor fusion):

- the grad pytree is flattened in jax's canonical deterministic order
  and partitioned into size-targeted buckets
  (``RAY_TPU_TRAIN_GRAD_BUCKET_BYTES``, ~4 MiB default; planning
  depends only on shapes/dtypes, so every rank derives byte-identical
  buckets — ``parallel/sharding.plan_buckets``);
- each bucket's allreduce launches **asynchronously**
  (``collective.allreduce_async`` → the group's background issue
  thread) as soon as the bucket is packed, so bucket k's comm overlaps
  the device→host fetch + packing of bucket k+1, the unpacking of
  completed buckets, and whatever compute the caller runs before
  ``result()`` — including the next microbatch's forward when used via
  ``sync_gradients_async``;
- ``result()`` waits all handles at the optimizer boundary, stamping
  each bucket's *actually blocked* time (the comm the backward failed
  to hide) into the metric + step-anatomy planes.

Composition: the quantized wire (PR 8) and the intra-host hierarchy
apply per bucket unchanged (each bucket is an ordinary float32-sum
allreduce); a poisoned gang (PR 5) fails every pending handle fast
with ``CollectiveGroupError``.

Determinism contract (pinned in tests/test_zz_bucket_ddp.py): all
ranks always return byte-identical synced grads (the ring/pair
exchange guarantees it per op). Bucketed-on vs the
``RAY_TPU_TRAIN_BUCKET_DDP=0`` kill switch (legacy single synchronous
allreduce over the whole flattened tree) is additionally
**bit-identical at world size 2** on the exact wire: the pairwise
exchange reduces every element as one two-operand IEEE add, which is
commutative, so bucket boundaries cannot change results. At larger
world sizes the ring's per-chunk reduction order depends on chunk
boundaries, so on-vs-off agree within float reassociation rounding
(the same caveat as the collective hierarchy) while staying exactly
rank-consistent either way.
"""
from __future__ import annotations

import time

from ray_tpu._private import memory_anatomy as _ma
from ray_tpu._private import profiling as _prof
from ray_tpu._private import telemetry as _tm


def _get_config(name):
    from ray_tpu._private.config import get_config

    return get_config(name)


class PendingGradSync:
    """In-flight bucketed gradient sync: every bucket's async allreduce
    has been launched; ``result(timeout)`` waits them in launch order,
    unpacks, and returns the synced grad pytree. Work the caller does
    between launch and ``result()`` overlaps ALL of the comm."""

    def __init__(self, group: str, treedef, leaves, launched,
                 world: int, average: bool, rank: int | None = None):
        self._group = group
        self._treedef = treedef
        self._leaves = leaves
        self._launched = launched    # [(indices, handle, t_launch)]
        self._world = world
        self._average = average
        self._rank = rank
        self._result = None
        self._out_leaves: list = [None] * len(leaves)
        self._next = 0               # harvest progress (retry-safe)

    @property
    def num_buckets(self) -> int:
        return len(self._launched)

    def poll(self) -> bool:
        """True once every bucket's allreduce completed."""
        return all(h.poll() for _, h, _ in self._launched)

    def result(self, timeout: float | None = None):
        """Wait every bucket at the optimizer boundary and return the
        synced pytree. Raises ``CollectiveGroupError`` if the gang was
        poisoned while buckets were in flight, ``TimeoutError`` on a
        wire stall (timeout-not-hang; default: the collective op
        timeout per bucket)."""
        if self._result is not None:
            return self._result
        from ray_tpu.parallel import sharding as _sh
        from ray_tpu.util import tracing as _tracing

        out_leaves = self._out_leaves
        tags = {"group": self._group}
        # resume from the first un-harvested bucket: a retry after a
        # failed/timed-out bucket must not re-observe the completed
        # buckets' wait/sync histograms (counts would exceed
        # buckets_total) nor re-unpack them
        while self._next < len(self._launched):
            b = self._next
            indices, handle, t_launch = self._launched[b]
            t0 = time.perf_counter()
            with _prof.record_span("train", f"grad_bucket_wait::{b}",
                                   {"group": self._group, "bucket": b}):
                with _tracing.span(f"grad_bucket_wait {b}", "INTERNAL",
                                   attributes={"group": self._group,
                                               "bucket": b}):
                    flat = handle.result(timeout)
            now = time.perf_counter()
            if _tm.ENABLED and self._rank is not None:
                # bucket landed: it is no longer in flight on the wire
                _ma.LEDGER.add_inflight(self._rank, -float(flat.nbytes))
            if _tm.ENABLED:
                _tm.observe("ray_tpu_train_bucket_wait_seconds",
                            now - t0, tags=tags)
                # launch→COMPLETION (the handle stamps done_at when the
                # op finishes on the issue thread) — NOT launch→harvest:
                # a caller that overlapped long compute before result()
                # must not inflate the bucket's apparent comm time (the
                # overlap-fraction panel divides wait by this)
                _tm.observe("ray_tpu_train_bucket_sync_seconds",
                            (handle.done_at or now) - t_launch,
                            tags=tags)
            if self._average:
                flat = flat / self._world
            _sh.unpack_bucket(flat, self._leaves, indices, out_leaves)
            self._next = b + 1
        self._result = _sh.unflatten_tree(self._treedef, out_leaves)
        # drop the launch-time references (packed buffers, raw grads)
        self._launched = []
        self._leaves = []
        return self._result


class _DoneSync:
    """Kill-switch / degenerate result: the sync already happened."""

    num_buckets = 0

    def __init__(self, result):
        self._result = result

    def poll(self) -> bool:
        return True

    def result(self, timeout: float | None = None):
        return self._result


def sync_gradients_async(grads, group_name: str = "train_dp", *,
                         average: bool = False,
                         bucket_bytes: int | None = None):
    """Launch the bucketed gradient sync and return a
    ``PendingGradSync`` immediately — overlap the comm with anything
    (the next microbatch's forward, metrics, logging), then call
    ``.result()`` at the optimizer boundary.

    With ``RAY_TPU_TRAIN_BUCKET_DDP=0`` the legacy path runs instead:
    one synchronous allreduce over the whole flattened tree (one op per
    dtype for mixed-dtype trees), completed before this returns."""
    from ray_tpu.parallel import sharding as _sh
    from ray_tpu.util import collective as col

    leaves, treedef = _sh.flatten_tree(grads)
    world = col.get_collective_group_size(group_name)
    if not leaves or world == 1:
        # world-1 sum is the identity (and average divides by 1):
        # skip the pack/allreduce/unpack round entirely
        return _DoneSync(grads)
    bucketed = bool(_get_config("train_bucket_ddp"))
    if bucket_bytes is None:
        bucket_bytes = int(_get_config("train_grad_bucket_bytes"))
    if not bucketed or not col.supports_async(group_name):
        # legacy: the whole tree as ONE synchronous allreduce (one
        # per dtype — a bucket must be contiguous in one dtype), the
        # exact pre-bucketing semantics the kill switch promises.
        # Also the degrade path for backends without async support
        # (xla) — the sync allreduce works there, so a grad sync must
        # not fail where the kill-switch path would succeed
        plan = _sh.plan_buckets(leaves, 1 << 62)
        out_leaves: list = [None] * len(leaves)
        for indices in plan:
            flat = col.allreduce(_sh.pack_bucket(leaves, indices),
                                 group_name)
            if average:
                flat = flat / world
            _sh.unpack_bucket(flat, leaves, indices, out_leaves)
        return _DoneSync(_sh.unflatten_tree(treedef, out_leaves))
    plan = _sh.plan_buckets(leaves, bucket_bytes)
    launched = []
    tags = {"group": group_name}
    rank = None
    if _tm.ENABLED:
        try:
            rank = col.get_rank(group_name)
        except Exception:
            rank = None
        if rank is not None:
            # exact by construction: the flatten is deterministic, so
            # this is THE grads footprint the sync moves for this rank
            _ma.LEDGER.note_train_state(
                "grads", rank, float(sum(l.nbytes for l in leaves)))
    for b, indices in enumerate(plan):
        # pack on the caller thread: bucket b's device→host fetch +
        # memcpy runs while buckets < b are already on the wire
        with _prof.record_span("train", f"grad_bucket_pack::{b}",
                               {"group": group_name, "bucket": b}):
            flat = _sh.pack_bucket(leaves, indices)
        if _tm.ENABLED:
            _tm.observe("ray_tpu_train_bucket_bytes", float(flat.nbytes),
                        tags=tags)
            _tm.counter_inc("ray_tpu_train_buckets_total", tags=tags)
            if rank is not None:
                _ma.LEDGER.add_inflight(rank, float(flat.nbytes))
        launched.append((indices, col.allreduce_async(flat, group_name),
                         time.perf_counter()))
    return PendingGradSync(group_name, treedef, leaves, launched, world,
                           average, rank=rank)


def sync_gradients(grads, group_name: str = "train_dp", *,
                   average: bool = False,
                   bucket_bytes: int | None = None):
    """Synchronize one grad pytree across the data-parallel gang and
    return the summed (or averaged) grads. Bucketed + async under the
    hood (see module docstring); the pack/unpack of neighboring buckets
    still overlaps each bucket's comm even though this call itself
    blocks until the full tree is synced."""
    # timeout=None = the collective op timeout per bucket (the wire's
    # failure detector of last resort) — bounded, never a silent hang
    return sync_gradients_async(
        grads, group_name, average=average,
        bucket_bytes=bucket_bytes).result(timeout=None)
