"""BackendExecutor — gang-schedules the worker group, runs backend setup,
drives the training loop (reference:
python/ray/train/_internal/backend_executor.py:42 — _create_placement_group
:137, start_training :314).
"""
from __future__ import annotations

import os
import threading
import time

import ray_tpu
from ray_tpu import exceptions as exc
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup
from ray_tpu.util.placement_group import (
    placement_group,
    remove_placement_group,
)


class _GangDeathMonitor:
    """Driver-side fast rank-death detector: subscribes to the GCS
    actor-lifecycle feed for the gang's worker actors so a rank death
    surfaces within seconds — as a named TrainWorkerGroupError listing
    the dead rank(s) — instead of whenever the next per-worker RPC
    happens to fail. Kill switch: RAY_TPU_TRAIN_DEATH_MONITOR=0
    (config `train_death_monitor`). Detection degrades gracefully to
    per-rank RPC failure attribution when off or unavailable."""

    def __init__(self, worker_group: WorkerGroup):
        self._rank_of = {w._actor_id: rank
                         for rank, w in enumerate(worker_group.workers)}
        self._lock = threading.Lock()
        self._dead: dict[int, str] = {}      # rank -> reason
        self._watch = None
        from ray_tpu._private.config import get_config

        if not get_config("train_death_monitor"):
            return
        try:
            from ray_tpu._private.pubsub import watch_actor_deaths

            self._watch = watch_actor_deaths(self._on_death)
        except Exception:
            pass   # detection degrades to per-rank RPC attribution

    def _on_death(self, actor_id, reason: str):
        rank = self._rank_of.get(actor_id)
        if rank is None:
            return
        with self._lock:
            self._dead.setdefault(rank, reason)
        # black box while the body is warm: the dump fan-out runs off
        # the pubsub callback thread (background), debounced so a
        # multi-rank death burst produces one dump
        try:
            from ray_tpu._private import flight_recorder as _fr

            _fr.trigger_dump("actor_death", background=True)
        except Exception:
            pass

    def dead_ranks(self) -> dict[int, str]:
        with self._lock:
            return dict(self._dead)

    def active(self) -> bool:
        """True only while the GCS subscription is live — callers should
        not pay an abort-check poll loop for a monitor that can never
        learn anything (kill switch off, or the subscribe failed)."""
        return self._watch is not None

    def stop(self):
        watch, self._watch = self._watch, None
        if watch is not None:
            watch.stop()


class _PreemptionMonitor:
    """Driver-side preemption-notice handler (multi-tenant control
    plane): subscribes to the GCS `pg_state` channel for the gang's
    placement group. On the PREEMPTION WARNING it pushes the notice to
    every rank (``TrainWorker.notify_preemption`` →
    ``session.preemption_warned()``) so the train loop can cut a
    checkpoint inside the grace window; when the preemption FIRES (the
    GCS reclaimed the bundles) it flips ``fired``, which
    ``next_results``'s abort check turns into ``TrainPreemptedError`` —
    the graceful teardown-requeue-resume path, not a failure. Rides
    PR 12's snapshot-resync so a missed feed message cannot hide a
    preemption."""

    def __init__(self, pg_id: bytes):
        self._pg_id = pg_id
        self._lock = threading.Lock()
        self._warned: dict | None = None
        self._fired = False
        self._notify = None          # set by attach(): notify_cb(grace_s)
        self._watch = None
        # CREATED observed for our pg — BackendExecutor.start hands
        # this to PlacementGroup.wait so the gang-schedule wait rides
        # THIS subscription instead of opening a second one per start
        self._created = threading.Event()
        try:
            from ray_tpu._private.api import _require_worker
            from ray_tpu._private.pubsub import watch_channel

            self._watch = watch_channel(
                "pg_state", self._on_msg, _require_worker().gcs.addr,
                poll_timeout=2.0)
        except Exception:
            pass   # degraded: preemption then surfaces as PG loss

    def attach(self, notify_cb):
        """``notify_cb(grace_s)`` fans the warning out to the workers
        (set once the worker group exists). A warning that arrived in
        the window between CREATED and attach is REPLAYED — dropping it
        would leave the ranks without their checkpoint-then-yield
        notice, defeating the grace window."""
        with self._lock:
            self._notify = notify_cb
            pending = dict(self._warned) if self._warned else None
        if pending is not None:
            try:
                notify_cb(pending["grace_s"])
            except Exception:
                pass

    def created_event(self) -> "threading.Event":
        return self._created

    def _on_msg(self, msg):
        if not isinstance(msg, dict):
            return
        if msg.get("event") == "resync":
            for row in (msg.get("snapshot") or ()):
                if isinstance(row, dict) and row.get("pg_id") == self._pg_id:
                    if row.get("state") == "CREATED":
                        self._created.set()
                    # a still-live deadline means we may have missed the
                    # warning push; `preempted_at` set means the FIRE
                    # itself was missed (stamped only by
                    # _fire_preemption — a PENDING/RESCHEDULING row
                    # alone could be a node-death reschedule, which
                    # must charge the failure budget, not requeue free)
                    if row.get("preempt_deadline"):
                        # the deadline is an epoch stamp: hand the loop
                        # the REMAINING window, not 0.0 — first-warning
                        # -wins would otherwise pin grace_s at zero and
                        # a cooperative loop would skip a checkpoint it
                        # had seconds to cut
                        self._handle_warning({"grace_s": max(
                            0.0, row["preempt_deadline"] - time.time())})
                    if row.get("preempted_at"):
                        self._handle_fired()
            return
        if msg.get("pg_id") != self._pg_id:
            return
        if msg.get("event") == "state" and msg.get("state") == "CREATED":
            self._created.set()
        elif msg.get("event") == "preempt_warning":
            self._handle_warning(msg)
        elif msg.get("event") == "state" and msg.get("state") == "PREEMPTED":
            self._handle_fired()

    def _handle_warning(self, msg):
        with self._lock:
            if self._warned is not None:
                return
            self._warned = {"grace_s": float(msg.get("grace_s") or 0.0)}
            notify = self._notify
        if notify is not None:
            try:
                notify(self._warned["grace_s"])
            except Exception:
                pass   # dying ranks can't take the notice; fire covers it


    def _handle_fired(self):
        with self._lock:
            self._fired = True

    def warned(self) -> dict | None:
        with self._lock:
            return dict(self._warned) if self._warned else None

    def fired(self) -> bool:
        with self._lock:
            return self._fired

    def active(self) -> bool:
        """True only while the pg_state subscription is live."""
        return self._watch is not None

    def stop(self):
        watch, self._watch = self._watch, None
        if watch is not None:
            watch.stop()


class Backend:
    """Pluggable per-framework setup (reference: train/backend.py Backend /
    BackendConfig — e.g. _TorchBackend sets up the process group,
    train/torch/config.py:123)."""

    def on_start(self, worker_group: WorkerGroup,
                 scaling: ScalingConfig):
        pass

    def on_shutdown(self, worker_group: WorkerGroup):
        pass


class JaxBackend(Backend):
    """TPU-native data-parallel backend.

    Two regimes (both covered by this one backend):
    - single-host gang (CI / one TPU host): workers form a host-relay
      collective group ("host" backend) for gradient allreduce — the analog
      of the reference wiring torch DDP over gloo.
    - multi-host TPU pod: one worker per host; each calls
      jax.distributed.initialize(coordinator, num_processes, process_id) so
      the workers jointly own the global device mesh and pjit compiles to
      ICI collectives. Enabled via JaxConfig(distributed=True).
    """

    def __init__(self, config: "JaxConfig"):
        self.config = config

    def on_start(self, worker_group, scaling):
        from ray_tpu.util import collective as col

        world = len(worker_group)
        group_name = self.config.group_name
        col.create_collective_group(
            [w for w in worker_group.workers], world, list(range(world)),
            backend=self.config.collective_backend, group_name=group_name)
        if self.config.distributed:
            # rank 0's host becomes the jax.distributed coordinator; the
            # port is negotiated on that host (a fixed default like
            # 127.0.0.1:9876 collides on real pods — advisor finding)
            coordinator = self.config.coordinator_address
            if coordinator is None:
                coordinator = worker_group.execute_single(
                    0, "free_coordinator_address")

            def _init_jax_distributed(rank, world_size, coordinator):
                import jax

                if not (hasattr(jax.distributed, "is_initialized") and
                        jax.distributed.is_initialized()):
                    jax.distributed.initialize(
                        coordinator_address=coordinator,
                        num_processes=world_size, process_id=rank)
                return True

            worker_group.execute(
                "run_setup",
                (_init_jax_distributed, (coordinator,), {}))

    def on_shutdown(self, worker_group):
        # Tear the group down on every member: drops the per-process state
        # (mailbox purge + stranded-shm sweep + poison clear) and kills
        # the rendezvous actor so the next incarnation under this group
        # name starts clean (advisor finding: the actor used to leak).
        # Surviving ranks answer fast; dead ranks resolve quickly as
        # ActorDiedError — the timeout only bounds pathological hangs so
        # a gang teardown can never wedge the restart loop.
        try:
            worker_group.execute("destroy_collective",
                                 self.config.group_name, timeout=60.0)
        except Exception:
            pass


class JaxConfig:
    """(reference analog: train/torch/config.py TorchConfig)"""

    def __init__(self, distributed: bool = False,
                 coordinator_address: str | None = None,
                 group_name: str = "train_dp",
                 collective_backend: str = "host"):
        self.distributed = distributed
        self.coordinator_address = coordinator_address
        self.group_name = group_name
        self.collective_backend = collective_backend

    def backend_cls(self):
        return JaxBackend(self)


class BackendExecutor:
    def __init__(self, backend_config: JaxConfig,
                 scaling: ScalingConfig):
        self.backend_config = backend_config
        self.scaling = scaling
        self.worker_group: WorkerGroup | None = None
        self.pg = None

    def start(self):
        bundles = self.scaling.as_placement_group_bundles()
        self.pg = placement_group(
            bundles, strategy=self.scaling.placement_strategy,
            job=getattr(self.scaling, "job", None),
            bundle_stages=getattr(self.scaling, "bundle_stages", None))
        # subscribe BEFORE waiting: a warning can only arrive once the
        # PG is CREATED, and the monitor must already be listening then.
        # The gang-schedule wait below rides THIS subscription (its
        # created_event) instead of opening a second pg_state
        # connection per start.
        self._preempt = _PreemptionMonitor(self.pg.id)
        try:
            ok = self.pg.wait(
                120.0,
                _created_event=(self._preempt.created_event()
                                if self._preempt.active() else None))
            if not ok:
                remove_placement_group(self.pg)
                self.pg = None
                from ray_tpu.exceptions import (
                    PlacementGroupUnschedulableError,
                )

                # typed so fit() can tell "still waiting for capacity
                # after a preemption requeue" (keep waiting, no budget
                # charge) from a real gang failure
                raise PlacementGroupUnschedulableError(
                    f"could not gang-schedule {len(bundles)} training "
                    f"bundles {bundles}: insufficient cluster resources")
            self.worker_group = WorkerGroup(
                self.scaling.num_workers, self.scaling.worker_resources(),
                placement_group=self.pg)
            # checkpoint-then-yield fan-out: the warning reaches every
            # rank's session so the train loop can checkpoint in the
            # grace window (fire-and-forget refs: a rank that can't
            # take the notice is torn down when the fire lands anyway);
            # attach replays a warning that landed before this point
            self._preempt.attach(lambda grace_s: [
                w.notify_preemption.remote(grace_s)
                for w in self.worker_group.workers])
            self.backend = self.backend_config.backend_cls()
            self.backend.on_start(self.worker_group, self.scaling)
        except BaseException:
            # a failure ANYWHERE in startup must release the monitor's
            # dedicated GCS connection + poll thread — a crash-looping
            # gang otherwise leaks one per retry (review finding)
            self._preempt.stop()
            raise
        self._monitor = _GangDeathMonitor(self.worker_group)
        self.worker_devices = self._record_group_devices()
        return self

    def _record_group_devices(self):
        """Gather per-worker device identities after backend setup (the
        collective/jax.distributed init just ran, so jax is loaded where
        it will be used) and record one train_group cluster event — the
        gang's rank -> device map, the join key between step events and
        the physical topology. Skipped entirely under the telemetry
        kill-switch; never fails startup."""
        from ray_tpu._private import events as _events

        if not _events.ENABLED:
            return None
        try:
            devices = self.worker_group.execute("device_identity",
                                                timeout=60.0)
        except Exception:
            return None
        _events.record("train_group",
                       num_workers=len(self.worker_group),
                       devices=devices)
        return devices

    def set_dataset_shards(self, name: str, shards: list):
        for worker, shard in zip(self.worker_group.workers, shards):
            ray_tpu.get(worker.set_dataset_shard.remote(name, shard))

    def start_training(self, train_fn, config):
        self._ckpt_root = (config or {}).get("_checkpoint_dir")
        self.worker_group.execute("start_training", train_fn, config)

    def checkpoint_resume_hint(self) -> dict | None:
        """Newest committed sharded generation under this run's root —
        what a gang restart will actually resume from. None when the
        run has no sharded root or no committed generation yet."""
        root = getattr(self, "_ckpt_root", None)
        if not root or not os.path.isdir(root):
            return None
        try:
            from ray_tpu.train.sharded_checkpoint import (
                summarize_checkpoints,
            )
            # cheap scan: manifest presence only, no shard re-hash —
            # this runs on the failure path and must never stall it
            for gen in summarize_checkpoints(root, digests=False):
                if gen.get("status") == "committed":
                    return {"step": gen.get("step"),
                            "path": gen.get("path"),
                            "world": gen.get("world")}
        except Exception:
            return None
        return None

    def next_results(self, timeout: float | None = None):
        """One row of results across the gang (or done/error markers).

        Blocks as long as the train functions run: the per-worker
        next_result only returns when a report arrives or the function
        ends, so a driver-side deadline would spuriously kill long steps
        (first-step XLA compile, big evals). Pass a timeout only to bound
        a run you are willing to abandon.

        A rank death surfaces here as TrainWorkerGroupError: the death
        monitor's pubsub knowledge is polled WHILE the gang call blocks
        (abort_check — a death interrupts the wait within seconds even
        if the transport never surfaces it), per-rank attribution comes
        from WorkerGroup.execute, and anything the monitor learned is
        merged into the raised error's dead_ranks.

        A FIRED preemption (the GCS reclaimed the gang's bundles after
        the grace window) surfaces as TrainPreemptedError through the
        same abort path — fit() treats it as a graceful requeue, not a
        failure."""
        monitor = getattr(self, "_monitor", None)
        pm = getattr(self, "_preempt", None)
        if pm is not None and pm.fired():
            raise self._preempted_error()
        death_check = (monitor.dead_ranks
                       if monitor is not None and monitor.active()
                       else None)
        abort_check = None
        if death_check is not None or pm is not None:
            def abort_check():
                known = dict(death_check()) if death_check else {}
                if pm is not None and pm.fired():
                    for rank in range(len(self.worker_group)):
                        known.setdefault(rank, "placement group preempted")
                return known
        try:
            rows = self.worker_group.execute(
                "next_result", timeout=timeout, abort_check=abort_check)
        except exc.TrainWorkerGroupError as e:
            if pm is not None and pm.fired():
                raise self._preempted_error() from e
            if monitor is not None:
                known = monitor.dead_ranks()
                if set(known) - set(e.dead_ranks):
                    for r, reason in known.items():
                        e.errors.setdefault(
                            r, exc.ActorDiedError("", reason))
                    raise exc.TrainWorkerGroupError(
                        e.errors,
                        set(e.dead_ranks) | set(known)) from e
            raise
        return rows

    def _preempted_error(self) -> "exc.TrainPreemptedError":
        pg_hex = self.pg.id.hex() if self.pg is not None else "?"
        return exc.TrainPreemptedError(
            message=f"training gang preempted: placement group {pg_hex} "
                    f"was reclaimed by a higher-priority job (graceful "
                    f"requeue — resumes from the latest checkpoint when "
                    f"capacity returns)")

    def shutdown(self):
        pm = getattr(self, "_preempt", None)
        if pm is not None:
            pm.stop()
            self._preempt = None
        monitor = getattr(self, "_monitor", None)
        if monitor is not None:
            monitor.stop()
            self._monitor = None
        if self.worker_group is not None:
            if getattr(self, "backend", None) is not None:
                self.backend.on_shutdown(self.worker_group)
            self.worker_group.shutdown()
            self.worker_group = None
        if self.pg is not None:
            try:
                remove_placement_group(self.pg)
            except Exception:
                pass
            self.pg = None
