"""Gang of training worker actors (reference:
python/ray/train/_internal/worker_group.py:92).

Each worker hosts the user's train function in a thread and streams
session.report results back through `next_result` calls. Workers are
plain actors; gang placement comes from the BackendExecutor's placement
group.
"""
from __future__ import annotations

import threading
import time

import ray_tpu
from ray_tpu._private import api as _api


class TrainWorker:
    """Actor body for one training worker."""

    def __init__(self, world_rank: int, world_size: int):
        from ray_tpu._private import fault_injection as _fi
        from ray_tpu.air import session as _session

        self.world_rank = world_rank
        self.world_size = world_size
        self.session = _session._Session(world_rank, world_size)
        self._thread = None
        self._device_identity = None
        # tag this process with its gang rank so rank-scoped chaos rules
        # (e.g. `kill_actor:rank1.next_result:#2`) target exactly one
        # member deterministically
        _fi.add_tag(f"rank{world_rank}")

    def device_identity(self) -> dict:
        """This worker's device identity (host/pid always; platform and
        device ids once the train function has imported jax). Resolved
        lazily and re-resolved until jax shows up, so the first report
        AFTER the backend initialized carries the real device info."""
        if (self._device_identity is None
                or self._device_identity.get("platform") is None):
            from ray_tpu._private.tpu_probe import local_device_identity

            self._device_identity = local_device_identity()
        return self._device_identity

    def setup_collective_group(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        col.init_collective_group(world_size, rank, backend, group_name)
        return rank

    def run_setup(self, setup_fn_and_args):
        """Backend hook (e.g. jax.distributed.initialize)."""
        fn, args, kwargs = setup_fn_and_args
        return fn(self.world_rank, self.world_size, *args, **kwargs)

    def free_coordinator_address(self):
        """A jax.distributed coordinator endpoint on THIS worker's host
        (port negotiated here instead of a collision-prone fixed default)."""
        import socket

        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        host = socket.gethostbyname(socket.gethostname())
        return f"{host}:{port}"

    def destroy_collective(self, group_name: str):
        from ray_tpu.util import collective as col

        return col.destroy_collective_group(group_name)

    def set_dataset_shard(self, name, shard):
        # Tag the shard with a per-rank consumer label so the streaming
        # data plane's telemetry (`ray_tpu_data_wait_seconds{consumer}`)
        # attributes data wait to the gang member it stalls — the
        # per-step "input gates the train step" signal.
        if hasattr(shard, "iter_batches"):
            try:
                shard._consumer = f"train/{name}/rank{self.world_rank}"
            except Exception:
                pass   # exotic shard types (plain lists) have no attrs
        self.session.dataset_shards[name] = shard

    def start_training(self, train_fn, config):
        from ray_tpu.air import session as _session

        if config is not None and "_resume_checkpoint" in config:
            # gang restart / resume_from_checkpoint: surfaced through
            # session.get_checkpoint() so the train loop can restore
            self.session.resume_checkpoint = config.pop(
                "_resume_checkpoint")
        if config is not None and "_checkpoint_dir" in config:
            # sharded-checkpoint generation root (trainer storage_path):
            # surfaced through session.get_checkpoint_dir() so
            # train.sharded_checkpoint save/restore need no path plumbing
            self.session.checkpoint_dir = config.pop("_checkpoint_dir")
        _session._set_session(self.session)

        def _run():
            from ray_tpu.parallel import step_anatomy

            # step 1 opens when the train function starts; each
            # session.report advances it (iteration == step_id), so
            # every collective/data/compile interval recorded by this
            # gang member fuses by step, not by wall-clock windows
            step_anatomy.start(rank=self.world_rank)
            try:
                train_fn(config) if config is not None else train_fn()
            except BaseException as e:  # noqa: BLE001
                self.session.error = e
            finally:
                step_anatomy.finish()
                self.session.finished.set()

        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="train-fn")
        self._thread.start()
        return True

    def next_result(self, timeout: float = 300.0):
        """Blocks for the next session.report() payload; returns
        {"done": True, "error": ...} when the function finishes.

        `timeout` only bounds the wait once the train thread is no longer
        alive: while the user function is still running it may legitimately
        go far longer than any fixed budget between reports (first-step XLA
        compiles, large eval passes), and killing the run for that would be
        spurious (advisor finding on the old hard 300s deadline)."""
        import queue as _q

        dead_deadline = None
        while True:
            try:
                row = self.session.results.get(timeout=0.1)
            except _q.Empty:
                if self.session.finished.is_set() and \
                        self.session.results.empty():
                    err = self.session.error
                    return {"done": True,
                            "error": err if err is None else
                            _stringify_error(err)}
                if self._thread is None or not self._thread.is_alive():
                    # measure against a monotonic deadline: counting 0.1s
                    # per Empty undercounts under load (each get() may
                    # block longer than its timeout), letting the
                    # deadline drift arbitrarily late
                    now = time.monotonic()
                    if dead_deadline is None:
                        dead_deadline = now + timeout
                    elif now >= dead_deadline:
                        raise TimeoutError(
                            "train thread gone without reporting a result")
            else:
                self._record_step_event(row)
                return row

    def _record_step_event(self, row: dict):
        """Tag one streamed step report with this worker's device
        identity (data-plane observability: which chip produced which
        step). Never fails the report path."""
        from ray_tpu._private import events as _events

        if not _events.ENABLED:
            return
        try:
            _events.record("train_step", rank=self.world_rank,
                           iteration=row.get("iteration"),
                           device=self.device_identity())
            # this process OWNS the jax backend, so it is the one place
            # live HBM gauges can come from without contending for the
            # chips (the raylet's subprocess probe can't run while
            # training holds them)
            from ray_tpu._private.tpu_probe import (
                publish_local_device_gauges,
            )

            publish_local_device_gauges()
        except Exception:
            pass

    def notify_preemption(self, grace_s: float):
        """Driver push on a PREEMPTION warning: surface it to the train
        loop through ``session.preemption_warned()`` so a cooperative
        loop checkpoints inside the grace window (checkpoint-then-yield)
        instead of losing everything since its last natural
        checkpoint."""
        self.session.preempt_notice = {"grace_s": float(grace_s),
                                       "warned_at": time.time()}
        return True

    def shutdown(self):
        return True


def _stringify_error(err: BaseException):
    # ship original if picklable, else a summary
    import pickle

    try:
        pickle.dumps(err)
        return err
    except Exception:
        return RuntimeError(f"{type(err).__name__}: {err}")


class WorkerGroup:
    def __init__(self, num_workers: int, resources_per_worker: dict,
                 placement_group=None):
        remote_cls = ray_tpu.remote(TrainWorker)
        self.workers = []
        for rank in range(num_workers):
            opts = dict(resources_per_worker)
            kwargs = {
                "num_cpus": opts.pop("CPU", 1),
                "resources": opts or None,
                # Gang members must NEVER be silently actor-restarted by
                # the raylet mid-incarnation: a restarted rank has fresh
                # collective counters and no session state, which
                # corrupts the group. Restarts are a GANG-level decision
                # (fit()'s FailureConfig loop tears down and rebuilds
                # everything from the latest checkpoint).
                "max_restarts": 0,
            }
            if "TPU" in (resources_per_worker or {}):
                kwargs["num_tpus"] = resources_per_worker["TPU"]
                kwargs["resources"] = {
                    k: v for k, v in (kwargs["resources"] or {}).items()
                    if k != "TPU"} or None
            if placement_group is not None:
                from ray_tpu.util.scheduling_strategies import (
                    PlacementGroupSchedulingStrategy,
                )

                kwargs["scheduling_strategy"] = \
                    PlacementGroupSchedulingStrategy(
                        placement_group=placement_group,
                        placement_group_bundle_index=rank)
            self.workers.append(
                remote_cls.options(**kwargs).remote(rank, num_workers))

    def __len__(self):
        return len(self.workers)

    # how often a gang-blocking execute consults abort_check while a
    # ref is still unresolved (the death monitor's fast-fail cadence)
    ABORT_POLL_S = 1.0

    def execute(self, method_name: str, *args, timeout=None,
                abort_check=None, **kwargs):
        """Run one method on every worker; results in gang (rank) order.

        Failures are attributed PER RANK: one dead worker no longer
        poisons the whole gang's result with whichever exception its
        `get` happened to raise first — every rank's ref is resolved,
        and the aggregate surfaces as TrainWorkerGroupError carrying
        {rank: error} plus the subset of ranks whose actor died.

        `abort_check` (optional, () -> {rank: reason}) is polled while a
        ref is pending: the moment it reports dead ranks the whole call
        raises, even if the RPC layer never surfaces the death (e.g. a
        partition where no TCP reset arrives) — this is how the gang
        death monitor's pubsub knowledge interrupts a blocked gang call
        within seconds instead of waiting out the transport."""
        from ray_tpu import exceptions as exc

        refs = [getattr(w, method_name).remote(*args, **kwargs)
                for w in self.workers]
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        results: list = [None] * len(refs)
        errors: dict[int, BaseException] = {}
        dead: list[int] = []

        def _resolve():
            for rank, ref in enumerate(refs):
                remaining = (None if deadline is None
                             else max(0.0, deadline - time.monotonic()))
                try:
                    results[rank] = ray_tpu.get(ref, timeout=remaining)
                except (exc.ActorDiedError, exc.ActorUnavailableError,
                        exc.WorkerCrashedError) as e:
                    errors[rank] = e
                    dead.append(rank)
                except Exception as e:  # noqa: BLE001 — per rank
                    errors[rank] = e

        if abort_check is None:
            _resolve()
        else:
            # Resolve on a waiter thread so the gang call blocks in ONE
            # get per rank: re-entering get(timeout=1.0) in a loop would
            # re-run its store/directory probe rounds (and reset its
            # poll escalation) every tick for the whole training run.
            # The main thread polls only in-process state — abort_check
            # is a lock-guarded dict copy, done.wait a futex.
            done = threading.Event()

            def _run():
                try:
                    _resolve()
                finally:
                    done.set()

            # daemon + abandoned on abort: teardown kills the gang's
            # workers (no_restart), which fails the pending get and
            # lets the waiter exit
            threading.Thread(target=_run, daemon=True,
                             name="gang-execute-waiter").start()
            while not done.wait(self.ABORT_POLL_S):
                known = abort_check()
                if known:
                    errs = dict(errors)
                    for r, reason in known.items():
                        errs.setdefault(
                            r, exc.ActorDiedError("", str(reason)))
                    raise exc.TrainWorkerGroupError(
                        errs, sorted(set(dead) | set(known)))
        if errors:
            raise exc.TrainWorkerGroupError(errors, dead)
        return results

    def execute_single(self, rank: int, method_name: str, *args, **kwargs):
        return ray_tpu.get(
            getattr(self.workers[rank], method_name).remote(*args, **kwargs))

    def shutdown(self):
        # no_restart suppresses any raylet-side restart race: a gang
        # teardown must leave zero members behind to leak stale frames
        # into the next incarnation
        for w in self.workers:
            try:
                ray_tpu.kill(w, no_restart=True)
            except Exception:
                pass
        self.workers = []
