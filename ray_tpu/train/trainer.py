"""Trainers (reference: python/ray/train/base_trainer.py:38 BaseTrainer.fit
:339; data_parallel_trainer.py:55 DataParallelTrainer).

JaxTrainer is the flagship: gang-schedules a worker per TPU host, wires the
data-parallel backend, streams results/checkpoints, returns a Result. The
reference wraps trainers in Tune trainables; here fit() drives the
BackendExecutor directly, and the Tune layer wraps Trainer the same way when
sweeping.
"""
from __future__ import annotations

import os
import time

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend_executor import BackendExecutor, JaxConfig


class BaseTrainer:
    def __init__(self, *, scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint: Checkpoint | None = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Adapter for the Tune layer: a function trainable running one
        fit() per trial config (reference: base_trainer.py:369)."""
        trainer = self

        def _trainable(config):
            from ray_tpu.air import session

            t = trainer.with_updated_config(config)
            result = t.fit()
            if result.error is not None:
                raise result.error
            session.report(result.metrics, checkpoint=result.checkpoint)

        return _trainable

    def with_updated_config(self, config: dict) -> "BaseTrainer":
        return self


class DataParallelTrainer(BaseTrainer):
    """(reference: data_parallel_trainer.py:55) Runs `train_loop_per_worker`
    on every worker of the gang; workers cooperate via the collective group
    (host backend) or a shared jax mesh (distributed mode)."""

    def __init__(self, train_loop_per_worker, *,
                 train_loop_config: dict | None = None,
                 backend_config: JaxConfig | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint: Checkpoint | None = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = dict(train_loop_config or {})
        self.backend_config = backend_config or JaxConfig()

    def with_updated_config(self, config: dict) -> "DataParallelTrainer":
        merged = {**self.train_loop_config, **config}
        return type(self)(
            self.train_loop_per_worker, train_loop_config=merged,
            backend_config=self.backend_config,
            scaling_config=self.scaling_config, run_config=self.run_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)

    def fit(self) -> Result:
        max_failures = self.run_config.failure_config.max_failures
        attempt = 0
        while True:
            try:
                return self._fit_once()
            except Exception:
                attempt += 1
                if max_failures != -1 and attempt > max_failures:
                    raise
                time.sleep(min(2.0 * attempt, 10.0))

    def _fit_once(self) -> Result:
        executor = BackendExecutor(self.backend_config,
                                   self.scaling_config).start()
        try:
            self._setup_datasets(executor)
            config = dict(self.train_loop_config)
            if self.resume_from_checkpoint is not None:
                config["_resume_checkpoint"] = self.resume_from_checkpoint
            executor.start_training(self.train_loop_per_worker, config)
            return self._drive(executor)
        finally:
            executor.shutdown()

    def _setup_datasets(self, executor):
        for name, ds in self.datasets.items():
            shards = self._shard_dataset(ds, self.scaling_config.num_workers)
            executor.set_dataset_shards(name, shards)

    @staticmethod
    def _shard_dataset(ds, n: int):
        # ray_tpu.data Dataset → split; plain lists/arrays → even chunks
        if hasattr(ds, "split"):
            return ds.split(n)
        size = len(ds)
        chunk = (size + n - 1) // n
        return [ds[i * chunk:(i + 1) * chunk] for i in range(n)]

    def _drive(self, executor) -> Result:
        history: list[dict] = []
        final_checkpoint = None
        storage = self.run_config.storage_path
        ckpt_dir = None
        if storage:
            ckpt_dir = os.path.join(
                storage, self.run_config.name or "train_run")
            os.makedirs(ckpt_dir, exist_ok=True)
        kept: list[str] = []
        num_keep = self.run_config.checkpoint_config.num_to_keep
        # Drive until RANK 0's stream ends. Workers report at different
        # cadences (e.g. HF callbacks report only on the world-zero
        # process), so a faster worker's completion sentinel must not
        # truncate rank 0's remaining reports — a finished worker's
        # next_result just keeps answering "done", making extra rounds
        # harmless.
        errors: list = []
        while True:
            rows = executor.next_results()
            rank0_done = False
            for rank, r in enumerate(rows):   # rows arrive in gang order
                if r.get("done"):
                    if r.get("error"):
                        errors.append(r["error"])
                    if rank == 0:
                        rank0_done = True
                    continue
                if rank != 0:
                    continue
                history.append(r["metrics"])
                if r.get("checkpoint") is not None:
                    final_checkpoint = r["checkpoint"]
                    if ckpt_dir:
                        path = os.path.join(
                            ckpt_dir, f"checkpoint_{r['iteration']:06d}")
                        final_checkpoint.to_directory(path)
                        kept.append(path)
                        if num_keep and len(kept) > num_keep:
                            import shutil

                            shutil.rmtree(kept.pop(0),
                                          ignore_errors=True)
            if errors:
                return Result(
                    metrics=history[-1] if history else {},
                    checkpoint=final_checkpoint,
                    error=errors[0], metrics_history=history,
                    path=ckpt_dir)
            if rank0_done:
                break
        return Result(metrics=history[-1] if history else {},
                      checkpoint=final_checkpoint,
                      metrics_history=history, path=ckpt_dir)


class JaxTrainer(DataParallelTrainer):
    """The canonical TPU trainer (the reference's TorchTrainer analog,
    train/torch/torch_trainer.py). Alias with jax-specific defaults."""
