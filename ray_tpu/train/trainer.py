"""Trainers (reference: python/ray/train/base_trainer.py:38 BaseTrainer.fit
:339; data_parallel_trainer.py:55 DataParallelTrainer).

JaxTrainer is the flagship: gang-schedules a worker per TPU host, wires the
data-parallel backend, streams results/checkpoints, returns a Result. The
reference wraps trainers in Tune trainables; here fit() drives the
BackendExecutor directly, and the Tune layer wraps Trainer the same way when
sweeping.
"""
from __future__ import annotations

import os
import time

from ray_tpu.air.checkpoint import Checkpoint
from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.air.result import Result
from ray_tpu.train.backend_executor import BackendExecutor, JaxConfig


class BaseTrainer:
    def __init__(self, *, scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint: Checkpoint | None = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.datasets = datasets or {}
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Adapter for the Tune layer: a function trainable running one
        fit() per trial config (reference: base_trainer.py:369)."""
        trainer = self

        def _trainable(config):
            from ray_tpu.air import session

            t = trainer.with_updated_config(config)
            result = t.fit()
            if result.error is not None:
                raise result.error
            session.report(result.metrics, checkpoint=result.checkpoint)

        return _trainable

    def with_updated_config(self, config: dict) -> "BaseTrainer":
        return self


class DataParallelTrainer(BaseTrainer):
    """(reference: data_parallel_trainer.py:55) Runs `train_loop_per_worker`
    on every worker of the gang; workers cooperate via the collective group
    (host backend) or a shared jax mesh (distributed mode)."""

    def __init__(self, train_loop_per_worker, *,
                 train_loop_config: dict | None = None,
                 backend_config: JaxConfig | None = None,
                 scaling_config: ScalingConfig | None = None,
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 resume_from_checkpoint: Checkpoint | None = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config, datasets=datasets,
                         resume_from_checkpoint=resume_from_checkpoint)
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = dict(train_loop_config or {})
        self.backend_config = backend_config or JaxConfig()

    def with_updated_config(self, config: dict) -> "DataParallelTrainer":
        merged = {**self.train_loop_config, **config}
        return type(self)(
            self.train_loop_per_worker, train_loop_config=merged,
            backend_config=self.backend_config,
            scaling_config=self.scaling_config, run_config=self.run_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint)

    def fit(self) -> Result:
        """Run training with gang-level fault tolerance.

        A failed attempt — a dead rank (TrainWorkerGroupError), a
        poisoned collective group (CollectiveGroupError in survivors),
        or any worker exception — tears the gang down cleanly (destroy
        the collective group, kill the workers with restarts suppressed,
        release the placement group), then rebuilds it and RESUMES the
        train loop from the latest successfully persisted checkpoint of
        the failed attempt (surfaced to workers via
        session.get_checkpoint()), up to FailureConfig.max_failures
        times. Exhausting the budget re-raises the last failure.

        Retry pacing reuses the unified control-plane policy
        (_private/retry.py): full-jitter exponential backoff, and each
        gang retry draws one token from the process-wide retry budget so
        restart storms surface through the budget-exhaustion event."""
        from ray_tpu._private import events as _events
        from ray_tpu._private import telemetry as _tm
        from ray_tpu._private.retry import RetryPolicy, default_budget

        fc = self.run_config.failure_config
        max_failures = fc.max_failures
        # non-Jax backends (TorchConfig) have no group name; the metric
        # tag falls back to the trainer run name
        group = getattr(self.backend_config, "group_name", None) \
            or self.run_config.name or "train"
        # gang restarts are heavyweight (teardown + reschedule + rebuild):
        # a larger base than the RPC default, same full-jitter shape.
        # Only backoff() is consulted — the retry budget here is
        # FailureConfig.max_failures (checked below), not the policy's
        # attempt cap
        from ray_tpu import exceptions as exc

        policy = RetryPolicy(base_backoff_s=0.5, max_backoff_s=10.0)
        attempt = 0
        preempt_requeues = 0
        self._group = group
        self._resume_ckpt = self.resume_from_checkpoint
        self._latest_checkpoint = None
        self._latest_iteration = None
        while True:
            self._attempt = attempt + 1
            try:
                return self._fit_once()
            except Exception as e:
                # GANG_FAILED event + flight-recorder dump were recorded
                # inside _fit_once, BEFORE its finally tore the gang
                # down — a post-teardown dump would capture only idle
                # pool workers, not the survivors' final spans
                preempted = isinstance(e, exc.TrainPreemptedError)
                if preempted:
                    # graceful degradation, not failure: a preempted
                    # gang re-queues and resumes from its checkpoint
                    # WITHOUT burning a max_failures token — the victim
                    # of another tenant's scale-up must not exhaust its
                    # own failure budget. The GCS's PREEMPTION_* events
                    # carry the audit trail.
                    preempt_requeues += 1
                    self._requeue_wait = True
                elif isinstance(e, exc.PlacementGroupUnschedulableError) \
                        and getattr(self, "_requeue_wait", False):
                    # the re-queued gang timed out WAITING for the
                    # preemptor to release capacity — still the
                    # preemption, not a new failure: keep waiting (the
                    # contract is "resumes when capacity returns", and
                    # charging the budget here would kill a preempted
                    # run whose preemptor merely outlives a few
                    # 120s placement windows)
                    preempt_requeues += 1
                else:
                    self._requeue_wait = False
                    attempt += 1
                    if max_failures != -1 and attempt > max_failures:
                        raise
                if getattr(fc, "restore_from_latest_checkpoint", True) \
                        and self._latest_checkpoint is not None:
                    self._resume_ckpt = self._latest_checkpoint
                # retry-budget event on every gang retry: take() records
                # budget exhaustion as a cluster event; the retry itself
                # proceeds regardless (failing training over an RPC-storm
                # budget would punish the victim)
                budget_ok = default_budget().take()
                _events.record("train_gang_retry", group=group,
                               attempt=attempt,
                               max_failures=max_failures,
                               budget_ok=budget_ok,
                               preempted=preempted,
                               preempt_requeues=preempt_requeues,
                               resume_iteration=self._latest_iteration)
                time.sleep(policy.backoff(max(1, attempt)))
                _tm.counter_inc("ray_tpu_train_gang_restarts_total",
                                tags={"group": group})
                _events.record("GANG_RESTARTED", group=group,
                               attempt=attempt,
                               preempted=preempted,
                               resume_iteration=self._latest_iteration)

    def _fit_once(self) -> Result:
        from ray_tpu._private import events as _events

        executor = None
        try:
            executor = BackendExecutor(self.backend_config,
                                       self.scaling_config).start()
            # the gang placed: a LATER unschedulable error is a fresh
            # capacity problem, not the preemption's requeue wait
            self._requeue_wait = False
            self._setup_datasets(executor)
            config = dict(self.train_loop_config)
            resume = getattr(self, "_resume_ckpt", None) \
                or self.resume_from_checkpoint
            if resume is not None:
                config["_resume_checkpoint"] = resume
            if self.run_config.storage_path:
                # generation root for sharded checkpoints: a sibling of
                # the rank-0 checkpoint_* dirs (which _drive's pruning
                # scans by prefix — gen_* dirs are invisible to it)
                config["_checkpoint_dir"] = os.path.join(
                    self.run_config.storage_path,
                    self.run_config.name or "train_run", "sharded")
            executor.start_training(self.train_loop_per_worker, config)
            return self._drive(executor)
        except Exception as e:
            from ray_tpu import exceptions as exc

            if isinstance(e, exc.TrainPreemptedError) or (
                    isinstance(e, exc.PlacementGroupUnschedulableError)
                    and getattr(self, "_requeue_wait", False)):
                # graceful preemption — including the requeued gang
                # timing out WAITING for the preemptor's capacity — is
                # NOT a failure: no GANG_FAILED, no flight-recorder
                # dump (a preemptor holding capacity for minutes would
                # otherwise force a full-cluster dump per 120s wait
                # cycle). The GCS's PREEMPTION_WARNED/PREEMPTION_FIRED
                # events are the audit trail, and the black box must
                # stay armed for real incidents.
                raise
            # The gang's surviving workers are STILL ALIVE here (the
            # finally below is what tears them down): record the
            # failure and cut the cluster black box now, so the dump
            # captures the survivors' final collective spans and step
            # records instead of post-teardown idle pool workers.
            # force ONLY on the first attempt: the death monitor's own
            # trigger may have fired moments earlier, BEFORE this
            # GANG_FAILED event existed, and the flagship dump must not
            # be debounced into missing it — but a crash-looping gang
            # retrying every backoff must not write one full cluster
            # dump per attempt (later attempts ride the 15s debounce).
            dead = sorted(getattr(e, "dead_ranks", ()) or ())
            attempt = getattr(self, "_attempt", 1)
            # what the restart will resume from: the newest COMMITTED
            # sharded generation (a torn one left by the crash is
            # invisible to restore and must not be advertised here)
            resume_hint = None
            if executor is not None:
                resume_hint = executor.checkpoint_resume_hint()
            _events.record("GANG_FAILED", group=self._group,
                           attempt=attempt, dead_ranks=list(dead),
                           resume_step=(resume_hint or {}).get("step"),
                           error=f"{type(e).__name__}: {e}")
            from ray_tpu._private import flight_recorder as _fr

            _fr.trigger_dump("GANG_FAILED", force=attempt == 1)
            raise
        finally:
            if executor is not None:
                executor.shutdown()

    def _setup_datasets(self, executor):
        for name, ds in self.datasets.items():
            shards = self._shard_dataset(ds, self.scaling_config.num_workers)
            executor.set_dataset_shards(name, shards)

    @staticmethod
    def _shard_dataset(ds, n: int):
        # ray_tpu.data Dataset → split; plain lists/arrays → even chunks
        if hasattr(ds, "split"):
            return ds.split(n)
        size = len(ds)
        chunk = (size + n - 1) // n
        return [ds[i * chunk:(i + 1) * chunk] for i in range(n)]

    def _drive(self, executor) -> Result:
        history: list[dict] = []
        final_checkpoint = None
        storage = self.run_config.storage_path
        ckpt_dir = None
        if storage:
            ckpt_dir = os.path.join(
                storage, self.run_config.name or "train_run")
            os.makedirs(ckpt_dir, exist_ok=True)
        kept: list[str] = []
        num_keep = self.run_config.checkpoint_config.num_to_keep
        if ckpt_dir:
            # re-seed the pruning window from disk: _drive runs once per
            # gang attempt, and without this a failed attempt's dirs fall
            # out of the window forever — each restart would strand up to
            # num_to_keep dirs and the run's disk use grows unboundedly
            kept = sorted(
                os.path.join(ckpt_dir, d) for d in os.listdir(ckpt_dir)
                if d.startswith("checkpoint_"))
        # Drive until RANK 0's stream ends. Workers report at different
        # cadences (e.g. HF callbacks report only on the world-zero
        # process), so a faster worker's completion sentinel must not
        # truncate rank 0's remaining reports — a finished worker's
        # next_result just keeps answering "done", making extra rounds
        # harmless.
        errors: dict[int, BaseException] = {}
        retryable = self.run_config.failure_config.max_failures != 0
        while True:
            rows = executor.next_results()
            rank0_done = False
            for rank, r in enumerate(rows):   # rows arrive in gang order
                if r.get("done"):
                    if r.get("error"):
                        errors.setdefault(rank, r["error"])
                    if rank == 0:
                        rank0_done = True
                    continue
                if rank != 0:
                    continue
                history.append(r["metrics"])
                if r.get("checkpoint") is not None:
                    final_checkpoint = r["checkpoint"]
                    if ckpt_dir:
                        path = os.path.join(
                            ckpt_dir, f"checkpoint_{r['iteration']:06d}")
                        final_checkpoint.to_directory(path)
                        if path in kept:
                            # session iteration counters restart per
                            # attempt, so a resumed gang re-uses dir
                            # names — treat the rewrite as newest, never
                            # as a prune candidate for itself
                            kept.remove(path)
                        kept.append(path)
                        if num_keep and len(kept) > num_keep:
                            import shutil

                            shutil.rmtree(kept.pop(0),
                                          ignore_errors=True)
                    # remembered across attempts: a gang restart resumes
                    # from here ("successfully persisted" = written to
                    # storage when storage is configured, else the last
                    # checkpoint streamed off the workers)
                    self._latest_checkpoint = final_checkpoint
                    self._latest_iteration = r.get("iteration")
            if errors:
                if retryable:
                    # hand the failure to fit()'s gang-restart loop with
                    # per-rank attribution (FailureConfig.max_failures
                    # != 0 opted into restart-from-checkpoint semantics)
                    from ray_tpu import exceptions as exc

                    raise exc.TrainWorkerGroupError(errors)
                first = errors[min(errors)]
                return Result(
                    metrics=history[-1] if history else {},
                    checkpoint=final_checkpoint,
                    error=first, metrics_history=history,
                    path=ckpt_dir)
            if rank0_done:
                break
        return Result(metrics=history[-1] if history else {},
                      checkpoint=final_checkpoint,
                      metrics_history=history, path=ckpt_dir)


class JaxTrainer(DataParallelTrainer):
    """The canonical TPU trainer (the reference's TorchTrainer analog,
    train/torch/torch_trainer.py). Alias with jax-specific defaults."""
