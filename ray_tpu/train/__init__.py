from ray_tpu.train.backend_executor import (  # noqa: F401
    Backend,
    BackendExecutor,
    JaxBackend,
    JaxConfig,
)
from ray_tpu.train.trainer import (  # noqa: F401
    BaseTrainer,
    DataParallelTrainer,
    JaxTrainer,
)
from ray_tpu.train.pipeline import (  # noqa: F401
    PipelineConfig,
    PipelineTrainer,
)
from ray_tpu.train import ddp  # noqa: F401
from ray_tpu.train.ddp import (  # noqa: F401
    sync_gradients,
    sync_gradients_async,
)
from ray_tpu.train.sharded_checkpoint import (  # noqa: F401
    restore_sharded,
    save_sharded,
    summarize_checkpoints,
)
from ray_tpu.train.worker_group import TrainWorker, WorkerGroup  # noqa: F401
from ray_tpu.train.predictor import (  # noqa: F401
    BatchPredictor,
    JaxPredictor,
    Predictor,
)
from ray_tpu.train.torch import (  # noqa: F401
    TorchConfig,
    TorchTrainer,
    prepare_model,
)
from ray_tpu.train.huggingface import (  # noqa: F401
    TransformersTrainer,
    prepare_trainer,
)
