"""Multi-slice MPMD pipeline training (the workload half of the
SPREAD_ACROSS_SLICES scheduler).

``PipelineTrainer`` partitions a model into P explicit stages, places
one Train sub-gang per TPU slice (stage-labeled placement-group bundles
under the SPREAD_ACROSS_SLICES strategy), and runs an actor-level
GPipe/1F1B microbatch schedule: activations and activation-gradients
flow stage-to-stage over the host send/recv plane (the PR 4 one-way
fast path), intra-stage data parallelism rides a per-stage collective
group, and the inter-stage hop optionally travels bf16/int8 (the
classic half-width activation wire — ``PipelineConfig.wire_dtype``).

The fault story composes from the existing planes rather than adding a
new one: a dead stage rank poisons the gang's collective group (PR 5),
pending sends/recvs on every OTHER stage raise ``CollectiveGroupError``
within milliseconds instead of wedging their schedule windows, and
``fit()``'s FailureConfig loop tears the whole pipeline down and
resumes it from the latest checkpoint (which carries EVERY stage's
params — rank 0 assembles them from a per-step gather). Preemption
warnings (PR 13) reach every rank's session and force a checkpoint at
the next step boundary inside the grace window.

Observability: each stage stamps its schedule stalls as
``pipeline_bubble`` step-anatomy activities and the
``ray_tpu_pipeline_*`` metrics, so ``summarize_steps()`` reports a
measured per-stage bubble fraction directly comparable to the
``(P-1)/(M+P-1)`` schedule theory (``schedule.py``).

``reference_run`` executes the identical math single-process — the
bit-for-bit loss oracle the E2E suite checks the distributed run
against (same float op order: forwards in microbatch order, backwards
accumulating in microbatch order, one fused ``lr/M`` update multiply).
"""
from __future__ import annotations

import time

import numpy as np

from ray_tpu.air.config import RunConfig, ScalingConfig
from ray_tpu.train.pipeline import schedule as _sched
from ray_tpu.train.pipeline.stage import (
    Stage,
    mse_loss,
    sgd_update,
    synth_microbatch,
)
from ray_tpu.train.trainer import DataParallelTrainer


class PipelineConfig:
    """Knobs of the actor-level pipeline schedule.

    - ``num_microbatches`` (M): microbatches per optimizer step — the
      bubble lever ((P-1)/(M+P-1)).
    - ``schedule``: "gpipe" (all-forward-then-all-backward) or "1f1b"
      (bounded activation memory, same bubble).
    - ``inflight_window``: GPipe ack window — how many un-acked
      activations a stage may post downstream before parking for a
      credit; None reads config ``pipeline_inflight_window`` (0 =
      unbounded). 1F1B's warmup depth is its inherent bound.
    - ``wire_dtype``: "bf16"/"int8" quantizes the inter-stage
      ACTIVATION hop (gradients stay exact unless ``quantize_grads``);
      None reads config ``pipeline_wire_dtype`` (default off = the
      bit-exact path the loss oracle requires).
    - ``checkpoint_every``: cut a full-pipeline checkpoint every k
      steps (0 = only at the final step and on preemption warnings).
    """

    def __init__(self, num_microbatches: int = 4, schedule: str = "gpipe",
                 inflight_window: int | None = None,
                 wire_dtype: str | None = None,
                 quantize_grads: bool | None = None,
                 checkpoint_every: int = 0,
                 group_name: str = "pipeline"):
        if schedule not in _sched.SCHEDULES:
            raise ValueError(f"schedule must be one of {_sched.SCHEDULES}, "
                             f"got {schedule!r}")
        if num_microbatches < 1:
            raise ValueError("num_microbatches must be >= 1")
        if wire_dtype is not None:
            # fail a typo'd format HERE, at construction on the driver —
            # not in a remote worker's first send, where FailureConfig
            # would burn its whole retry budget on a deterministic
            # config error (None is NOT normalized away: it means
            # "defer to the pipeline_wire_dtype config default")
            from ray_tpu.util.collective import wire as _wire

            _wire.normalize_format(wire_dtype)
        self.num_microbatches = int(num_microbatches)
        self.schedule = schedule
        self.inflight_window = inflight_window
        self.wire_dtype = wire_dtype
        self.quantize_grads = quantize_grads
        self.checkpoint_every = int(checkpoint_every)
        self.group_name = group_name


def _resolve_wire(wire_dtype):
    from ray_tpu.util.collective import wire as _wire

    if wire_dtype is None:
        from ray_tpu._private.config import get_config

        wire_dtype = get_config("pipeline_wire_dtype")
    return _wire.normalize_format(wire_dtype)


def _pipeline_worker_loop(config: dict):
    """One gang member's schedule executor (runs as the Train worker's
    train function; global rank r = stage r // R, stage-rank r % R)."""
    from ray_tpu._private import fault_injection as _fi
    from ray_tpu._private import telemetry as _tm
    from ray_tpu._private.config import get_config
    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.parallel import step_anatomy
    from ray_tpu.util import collective as col

    spec = config["_pipeline_spec"]
    rank = session.get_world_rank()
    num_stages = int(spec["num_stages"])
    ranks_per = int(spec["ranks_per_stage"])
    microbatches = int(spec["num_microbatches"])
    stage_idx, stage_rank = divmod(rank, ranks_per)
    # chaos scoping: seeded schedules like
    # `kill_actor:stage1-rank0.next_result:#2` land on exactly one
    # deterministic pipeline position
    _fi.add_tag(f"stage{stage_idx}-rank{stage_rank}")
    stage: Stage = spec["stages"][stage_idx]
    group = spec["group_name"]
    lr = float(spec["learning_rate"])
    loss_fn = mse_loss if spec["loss"] == "mse" else spec["loss"]
    wire = _resolve_wire(spec["wire_dtype"])
    quant_grads = spec["quantize_grads"]
    if quant_grads is None:
        quant_grads = bool(get_config("pipeline_quantize_grads"))
    window = spec["inflight_window"]
    if window is None:
        window = int(get_config("pipeline_inflight_window"))
    # the ack credit protocol assumes GPipe's phase split (all acks
    # precede all grads on the down->up channel); 1F1B's warmup depth
    # already bounds in-flight, so the window only arms under gpipe
    window = int(window) if spec["schedule"] == "gpipe" else 0

    params = stage.init_params(
        np.random.default_rng(int(spec["seed"]) + stage_idx))
    start_step = 0
    ckpt = session.get_checkpoint()
    if ckpt is not None:
        state = ckpt.to_dict()
        start_step = int(state["step"]) + 1
        params = [np.asarray(p, np.float32).copy()
                  for p in state["stage_params"][stage_idx]]

    stage_group = None
    if ranks_per > 1:
        # intra-stage data-parallel subgroup (grad allreduce rides the
        # normal pipelined ring inside the stage's slice)
        stage_group = f"{group}:stage{stage_idx}"
        col.init_collective_group(ranks_per, stage_rank, "host",
                                  stage_group)
    up = rank - ranks_per if stage_idx > 0 else None
    down = rank + ranks_per if stage_idx < num_stages - 1 else None
    actions = _sched.build_schedule(spec["schedule"], stage_idx,
                                    num_stages, microbatches)

    shard = session.get_dataset_shard(spec["dataset_name"]) \
        if stage_idx == 0 else None
    batch_iter = None
    if shard is not None and hasattr(shard, "iter_batches"):
        # streaming data plane feeds stage 0: one bounded-prefetch
        # iterator across the whole run (epoch semantics belong to the
        # dataset; the loop just keeps pulling microbatches)
        def _batches():
            while True:
                for b in shard.iter_batches(
                        batch_size=int(spec["microbatch_size"])):
                    yield b

        batch_iter = _batches()

    def _next_microbatch(step: int, mb: int):
        if batch_iter is not None:
            b = next(batch_iter)
            return (np.asarray(b["x"], np.float32),
                    np.asarray(b["y"], np.float32))
        return synth_microbatch(int(spec["seed"]) + stage_rank, step, mb,
                                int(spec["microbatch_size"]),
                                stage.in_dim or 1,
                                int(spec["out_dim"]))

    tags = {"group": group, "stage": str(stage_idx)}
    _ACK = np.zeros(1, np.int8)

    for step in range(start_step, int(spec["num_steps"])):
        step_t0 = time.monotonic()
        bubble = 0.0

        def _stalled(fn):
            """Run one blocking schedule wait, stamping it as bubble
            time (step-anatomy `pipeline_bubble` + the step total)."""
            nonlocal bubble
            t0 = time.monotonic()
            out = fn()
            t1 = time.monotonic()
            bubble += t1 - t0
            step_anatomy.record_activity("pipeline_bubble", t0, t1,
                                         stage=stage_idx)
            return out

        grads = [np.zeros_like(p) for p in params]
        caches: dict[int, object] = {}
        pending_gy: dict[int, np.ndarray] = {}
        loss_sum = 0.0
        sent = acked = 0
        drained = False
        for kind, mb in actions:
            if kind == "fwd":
                if up is None:
                    x, y = _next_microbatch(step, mb)
                else:
                    x = _stalled(lambda: col.recv(up, group))
                    y = col.recv(up, group)
                out, ctx = stage.forward(params, x)
                caches[mb] = ctx
                if down is not None:
                    if window and sent - acked >= window:
                        _stalled(lambda: col.recv(down, group))
                        acked += 1
                    col.send(out, down, group, wire_dtype=wire)
                    col.send(y, down, group)
                    sent += 1
                else:
                    loss, gy = loss_fn(out, y)
                    loss_sum += float(loss)
                    pending_gy[mb] = gy
                if up is not None and window:
                    col.send(_ACK, up, group)
            else:  # bwd
                if down is not None and window and not drained:
                    # GPipe phase boundary: the down->up channel holds
                    # the remaining fwd-phase ack credits ahead of the
                    # first gradient — drain them in order
                    for _ in range(sent - acked):
                        _stalled(lambda: col.recv(down, group))
                        acked += 1
                    drained = True
                if down is not None:
                    gy = _stalled(lambda: col.recv(down, group))
                else:
                    gy = pending_gy.pop(mb)
                gx, g = stage.backward(params, caches.pop(mb), gy)
                for i in range(len(grads)):
                    grads[i] += g[i]
                if up is not None:
                    col.send(gx, up, group,
                             wire_dtype=wire if quant_grads else None)
        if stage_group is not None:
            grads = [np.asarray(col.allreduce(g, stage_group))
                     for g in grads]
            if down is None:
                loss_sum = float(np.asarray(col.allreduce(
                    np.array([loss_sum], np.float64), stage_group))[0]
                    ) / ranks_per
        sgd_update(params, grads, lr,
                   1.0 / (microbatches * ranks_per))

        # ---- step-end consensus round: loss to rank 0, checkpoint
        # decision, preemption notice. One SMALL allgather keeps every
        # rank's collective order identical (the decision must be
        # uniform — a rank checkpointing alone would desync the group);
        # the actual params then move POINT-TO-POINT, each stage's once
        # straight to rank 0 — an allgather would broadcast the whole
        # model to every rank (O(world x model bytes) on the very
        # inter-slice links the pipeline exists to relieve).
        scheduled = bool(spec["checkpoint_every"]) and \
            (step + 1) % spec["checkpoint_every"] == 0
        final = step == int(spec["num_steps"]) - 1
        row = {"stage": stage_idx,
               "loss_sum": loss_sum if (down is None and stage_rank == 0)
               else None,
               "warned": session.preemption_warned() is not None}
        summary = col.allgather_object(row, group)
        want_ckpt = scheduled or final or any(r["warned"] for r in summary)
        stage_params = None
        if want_ckpt:
            import pickle as _pickle

            from ray_tpu.parallel import step_anatomy as _sa

            # checkpoint assembly is a step-loop stall: attribute it in
            # the same anatomy lane the sharded writer uses, so "why was
            # step k slow" answers "checkpoint", not "mystery bubble"
            _asm_t0 = time.monotonic()
            if rank == 0:
                stage_params = {0: [np.array(p) for p in params]}
                for s in range(1, num_stages):
                    blob = np.asarray(col.recv(s * ranks_per, group))
                    stage_params[s] = _pickle.loads(blob.tobytes())
            elif stage_rank == 0:
                col.send(np.frombuffer(_pickle.dumps(
                    [np.array(p) for p in params]), np.uint8), 0, group)
            try:
                _sa.record_activity("checkpoint", _asm_t0,
                                    time.monotonic(), blocking=True,
                                    phase="assemble", step=step)
            except Exception:
                pass

        step_wall = time.monotonic() - step_t0
        if _tm.ENABLED:
            _tm.observe("ray_tpu_pipeline_bubble_seconds", bubble,
                        tags=tags)
            _tm.observe("ray_tpu_pipeline_step_seconds", step_wall,
                        tags=tags)
            _tm.counter_inc("ray_tpu_pipeline_microbatches_total",
                            float(microbatches),
                            tags={**tags, "phase": "fwd"})
            _tm.counter_inc("ray_tpu_pipeline_microbatches_total",
                            float(microbatches),
                            tags={**tags, "phase": "bwd"})
        metrics = {"step": step, "stage": stage_idx,
                   "bubble_s": round(bubble, 6),
                   "step_wall_s": round(step_wall, 6),
                   "bubble_fraction": (round(bubble / step_wall, 6)
                                       if step_wall > 0 else 0.0)}
        checkpoint = None
        if rank == 0:
            metrics["loss"] = next(
                r["loss_sum"] for r in summary
                if r["loss_sum"] is not None) / microbatches
            if want_ckpt:
                checkpoint = Checkpoint.from_dict(
                    {"step": step, "stage_params": stage_params})
        session.report(metrics, checkpoint=checkpoint)

    if stage_group is not None:
        # drop the per-stage subgroup so its rendezvous actor doesn't
        # outlive the gang (the main group is destroyed by the backend's
        # on_shutdown; subgroups are this loop's to clean up)
        try:
            col.destroy_collective_group(stage_group)
        except Exception:
            pass


class PipelineTrainer(DataParallelTrainer):
    """Stage-partitioned MPMD pipeline training over one gang of
    P x ranks_per_stage workers, placed one stage per TPU slice.

    ``stages`` is the partitioned model (one ``Stage`` per pipeline
    stage); data enters at stage 0 (a ``datasets={"train": ...}`` shard
    through the streaming data plane, or the built-in deterministic
    synthetic feed), the loss lives on the last stage, and rank 0
    streams per-step metrics + full-pipeline checkpoints back through
    the normal Train result path — so FailureConfig gang restarts,
    preemption requeues and Tune wrapping all behave exactly as for a
    data-parallel gang."""

    def __init__(self, stages: list, *,
                 loss="mse", learning_rate: float = 0.05,
                 num_steps: int = 4, microbatch_size: int = 8,
                 seed: int = 0,
                 pipeline_config: PipelineConfig | None = None,
                 ranks_per_stage: int = 1,
                 resources_per_worker: dict | None = None,
                 placement_strategy: str = "SPREAD_ACROSS_SLICES",
                 dataset_name: str = "train",
                 run_config: RunConfig | None = None,
                 datasets: dict | None = None,
                 job: str | None = None,
                 resume_from_checkpoint=None):
        if not stages:
            raise ValueError("need at least one pipeline stage")
        pc = pipeline_config or PipelineConfig()
        num_stages = len(stages)
        num_workers = num_stages * ranks_per_stage
        self.pipeline_config = pc
        self.num_stages = num_stages
        self.ranks_per_stage = int(ranks_per_stage)
        spec = {
            "stages": list(stages),
            "num_stages": num_stages,
            "ranks_per_stage": int(ranks_per_stage),
            "num_microbatches": pc.num_microbatches,
            "schedule": pc.schedule,
            "inflight_window": pc.inflight_window,
            "wire_dtype": pc.wire_dtype,
            "quantize_grads": pc.quantize_grads,
            "checkpoint_every": pc.checkpoint_every,
            "group_name": pc.group_name,
            "learning_rate": float(learning_rate),
            "loss": loss,
            "num_steps": int(num_steps),
            "microbatch_size": int(microbatch_size),
            "out_dim": int(getattr(stages[-1], "out_dim", 1) or 1),
            "seed": int(seed),
            "dataset_name": dataset_name,
        }
        from ray_tpu.train.backend_executor import JaxConfig

        scaling = ScalingConfig(
            num_workers=num_workers,
            resources_per_worker=dict(resources_per_worker or {"CPU": 1}),
            placement_strategy=placement_strategy,
            bundle_stages=([i // ranks_per_stage
                            for i in range(num_workers)]
                           if placement_strategy == "SPREAD_ACROSS_SLICES"
                           else None),
            job=job)
        super().__init__(
            _pipeline_worker_loop,
            train_loop_config={"_pipeline_spec": spec},
            backend_config=JaxConfig(group_name=pc.group_name,
                                     collective_backend="host"),
            scaling_config=scaling, run_config=run_config,
            datasets=datasets,
            resume_from_checkpoint=resume_from_checkpoint)

    def _setup_datasets(self, executor):
        # only stage 0's ranks consume input: shard across the stage's
        # data-parallel width, not the whole gang; later stages receive
        # activations, not batches
        r = self.ranks_per_stage
        for name, ds in self.datasets.items():
            shards = list(self._shard_dataset(ds, r))
            shards += [None] * (self.num_stages * r - r)
            executor.set_dataset_shards(name, shards)

    def _drive(self, executor):
        self._record_gang_event(executor)
        return super()._drive(executor)

    def _record_gang_event(self, executor):
        """PIPELINE_GANG_STARTED with the stage -> slice placement the
        SPREAD_ACROSS_SLICES scheduler chose (driver-side: the PG is
        CREATED by the time _drive runs). Never fails training."""
        from ray_tpu._private import events as _events

        if not _events.ENABLED:
            return
        try:
            from ray_tpu._private import api as _api

            worker = _api._require_worker()
            snap = worker.gcs.call("get_placement_group",
                                   pg_id=executor.pg.id)
            nodes = {n["NodeID"]: n for n in worker.gcs.call("get_nodes")}
            labels = snap.get("Stages") or \
                list(range(len(snap["BundleNodes"])))
            stage_slices: dict = {}
            for lab, nid in zip(labels, snap["BundleNodes"]):
                tpu = (nodes.get(nid) or {}).get("tpu") or {}
                stage_slices.setdefault(str(lab), set()).add(
                    str(tpu.get("slice_id")))
            pc = self.pipeline_config
            _events.record(
                "PIPELINE_GANG_STARTED", group=pc.group_name,
                num_stages=self.num_stages,
                ranks_per_stage=self.ranks_per_stage,
                microbatches=pc.num_microbatches, schedule=pc.schedule,
                stage_slices={k: sorted(v)
                              for k, v in stage_slices.items()})
        except Exception:
            pass


def reference_run(stages: list, *, num_steps: int, num_microbatches: int,
                  microbatch_size: int, learning_rate: float,
                  seed: int = 0, loss="mse") -> dict:
    """Single-process oracle executing the pipeline's EXACT math —
    same init rngs, same synthetic feed, same float op order (forwards
    and loss accumulation in microbatch order, per-stage gradient
    accumulation in microbatch order, one fused ``lr/M`` update
    multiply) — so a distributed run with the exact wire must match its
    per-step losses and final params bit for bit, per seed."""
    loss_fn = mse_loss if loss == "mse" else loss
    params = [st.init_params(np.random.default_rng(seed + i))
              for i, st in enumerate(stages)]
    in_dim = stages[0].in_dim or 1
    out_dim = int(getattr(stages[-1], "out_dim", 1) or 1)
    m = int(num_microbatches)
    losses = []
    for step in range(int(num_steps)):
        grads = [[np.zeros_like(p) for p in ps] for ps in params]
        caches, gys = [], []
        loss_sum = 0.0
        for mb in range(m):
            x, y = synth_microbatch(seed, step, mb, microbatch_size,
                                    in_dim, out_dim)
            ctxs = []
            h = x
            for st, ps in zip(stages, params):
                h, ctx = st.forward(ps, h)
                ctxs.append(ctx)
            step_loss, gy = loss_fn(h, y)
            loss_sum += float(step_loss)
            caches.append(ctxs)
            gys.append(gy)
        for mb in range(m):
            gy = gys[mb]
            for si in reversed(range(len(stages))):
                gx, g = stages[si].backward(params[si], caches[mb][si], gy)
                for i in range(len(grads[si])):
                    grads[si][i] += g[i]
                gy = gx
        for si in range(len(stages)):
            sgd_update(params[si], grads[si], learning_rate, 1.0 / m)
        losses.append(loss_sum / m)
    return {"losses": losses, "params": params}
