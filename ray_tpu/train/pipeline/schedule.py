"""Pipeline microbatch schedules (GPipe / 1F1B) as pure functions.

A schedule is the per-stage ordered action list ``[("fwd", mb), ("bwd",
mb), ...]`` an MPMD pipeline stage executes for ONE optimizer step.
Both sides of every inter-stage channel derive their send/recv order
from the same schedule, so the host p2p plane's per-channel sequence
counters pair messages without any tagging beyond arrival order.

Both schedules issue backwards in microbatch order 0..M-1 (GPipe could
equally run them reversed, but a FIXED order shared with 1F1B and with
``trainer.reference_run`` is what makes the single-gang loss oracle
bit-for-bit: float gradient accumulation is order-sensitive).

Grounded in "Scaling Deep Learning Training with MPMD Pipeline
Parallelism" (arXiv:2412.14374: JaxPP's 1F1B task schedules) and the
GPipe bubble analysis: with P stages and M microbatches the schedule
leaves each stage idle for (P-1) of the (M+P-1) microbatch slots per
phase — ``theoretical_bubble_fraction`` is the number the step-anatomy
plane's measured per-stage bubble is checked against.
"""
from __future__ import annotations

SCHEDULES = ("gpipe", "1f1b")


def gpipe_schedule(stage: int, num_stages: int,
                   num_microbatches: int) -> list[tuple[str, int]]:
    """All forwards, then all backwards (the flush-per-step schedule).
    Peak in-flight activations = M on every stage."""
    _check(stage, num_stages, num_microbatches)
    m = num_microbatches
    return [("fwd", i) for i in range(m)] + [("bwd", i) for i in range(m)]


def one_f_one_b_schedule(stage: int, num_stages: int,
                         num_microbatches: int) -> list[tuple[str, int]]:
    """Non-interleaved 1F1B: ``warmup`` forwards, then alternating
    fwd/bwd pairs, then the cooldown backwards. Peak in-flight
    activations on stage ``s`` is ``min(M, P - s)`` — the schedule's
    inherent bounded window (deepest at stage 0, 1 at the last stage),
    vs GPipe's M everywhere. Backward order is 0..M-1, same as GPipe."""
    _check(stage, num_stages, num_microbatches)
    m, p = num_microbatches, num_stages
    warmup = min(m, p - 1 - stage)
    actions: list[tuple[str, int]] = [("fwd", i) for i in range(warmup)]
    for i in range(m - warmup):
        actions.append(("fwd", warmup + i))
        actions.append(("bwd", i))
    actions.extend(("bwd", i) for i in range(m - warmup, m))
    return actions


def build_schedule(name: str, stage: int, num_stages: int,
                   num_microbatches: int) -> list[tuple[str, int]]:
    if name == "gpipe":
        return gpipe_schedule(stage, num_stages, num_microbatches)
    if name == "1f1b":
        return one_f_one_b_schedule(stage, num_stages, num_microbatches)
    raise ValueError(
        f"unknown pipeline schedule {name!r}: expected one of {SCHEDULES}")


def max_inflight(actions: list[tuple[str, int]]) -> int:
    """Peak number of microbatches forwarded but not yet backwarded —
    the stage's activation-memory high-water mark under this schedule."""
    live = peak = 0
    for kind, _ in actions:
        live += 1 if kind == "fwd" else -1
        peak = max(peak, live)
    return peak


def theoretical_bubble_fraction(num_stages: int,
                                num_microbatches: int) -> float:
    """(P-1)/(M+P-1): the fraction of a step each stage spends idle
    under a flush-per-step schedule with uniform microbatch cost (both
    GPipe and non-interleaved 1F1B share it — 1F1B bounds MEMORY, not
    the bubble)."""
    p, m = int(num_stages), int(num_microbatches)
    if p <= 1:
        return 0.0
    return (p - 1) / (m + p - 1)


def _check(stage: int, num_stages: int, num_microbatches: int):
    if not 0 <= stage < num_stages:
        raise ValueError(f"stage {stage} out of range for {num_stages}")
    if num_microbatches < 1:
        raise ValueError("need at least one microbatch")
