"""Multi-slice MPMD pipeline parallelism (stage-per-slice training).

The workload the ICI-topology-aware scheduler unlocks: a
SPREAD_ACROSS_SLICES placement group lands each pipeline stage's
sub-gang contiguous inside its own TPU slice, and ``PipelineTrainer``
runs an actor-level GPipe/1F1B microbatch schedule with activations
hopping stage-to-stage over the host send/recv plane (optionally bf16
on the wire). See README "Pipeline parallelism & topology".
"""
from ray_tpu.train.pipeline.schedule import (
    build_schedule,
    gpipe_schedule,
    max_inflight,
    one_f_one_b_schedule,
    theoretical_bubble_fraction,
)
from ray_tpu.train.pipeline.stage import (
    DenseStage,
    SleepStage,
    Stage,
    mse_loss,
    sgd_update,
    synth_microbatch,
)
from ray_tpu.train.pipeline.trainer import (
    PipelineConfig,
    PipelineTrainer,
    reference_run,
)

__all__ = [
    "DenseStage", "PipelineConfig", "PipelineTrainer", "SleepStage",
    "Stage", "build_schedule", "gpipe_schedule", "max_inflight",
    "mse_loss", "one_f_one_b_schedule", "reference_run", "sgd_update",
    "synth_microbatch", "theoretical_bubble_fraction",
]
