"""Pipeline stage contract + numpy reference stages.

A stage is the unit ``PipelineTrainer`` places one-per-slice: it owns a
slice of the model's layers and exposes an explicit forward/backward
pair over host numpy arrays (the inter-stage hop is host memory either
way — activations cross the slice boundary over the send/recv plane,
not ICI). Everything is float32 and deterministic, which is what lets
``trainer.reference_run`` serve as a bit-for-bit single-gang oracle.

A jax stage fits the same contract (forward returning a residual ctx,
backward consuming it); the reference stages below keep the plane
testable on CPU-only CI.
"""
from __future__ import annotations

import time

import numpy as np


class Stage:
    """One pipeline stage: parameters + explicit forward/backward."""

    #: input/output feature widths — the trainer uses stage 0's in_dim
    #: and the last stage's out_dim to synthesize data when no dataset
    #: shard feeds stage 0.
    in_dim: int = 0
    out_dim: int = 0

    def init_params(self, rng: np.random.Generator) -> list[np.ndarray]:
        raise NotImplementedError

    def forward(self, params: list, x: np.ndarray):
        """-> (y, ctx): activation for the next stage + residuals the
        backward needs."""
        raise NotImplementedError

    def backward(self, params: list, ctx, gy: np.ndarray):
        """-> (gx, grads): gradient for the previous stage + this
        stage's parameter gradients (same structure as params)."""
        raise NotImplementedError


class DenseStage(Stage):
    """``act(W @ x + b)`` — the reference building block."""

    def __init__(self, in_dim: int, out_dim: int, activation: str = "tanh"):
        if activation not in ("tanh", "relu", "none"):
            raise ValueError(f"unknown activation {activation!r}")
        self.in_dim = int(in_dim)
        self.out_dim = int(out_dim)
        self.activation = activation

    def init_params(self, rng):
        scale = np.float32(1.0 / np.sqrt(self.in_dim))
        w = (rng.standard_normal((self.in_dim, self.out_dim))
             .astype(np.float32) * scale)
        b = np.zeros(self.out_dim, np.float32)
        return [w, b]

    def forward(self, params, x):
        w, b = params
        pre = x @ w + b
        if self.activation == "tanh":
            y = np.tanh(pre)
        elif self.activation == "relu":
            y = np.maximum(pre, np.float32(0.0))
        else:
            y = pre
        return y, (x, pre)

    def backward(self, params, ctx, gy):
        w, _b = params
        x, pre = ctx
        if self.activation == "tanh":
            t = np.tanh(pre)
            gz = gy * (np.float32(1.0) - t * t)
        elif self.activation == "relu":
            gz = gy * (pre > 0).astype(np.float32)
        else:
            gz = gy
        gw = x.T @ gz
        gb = gz.sum(axis=0)
        gx = gz @ w.T
        return gx, [gw, gb]


class SleepStage(Stage):
    """Pass-through stage with a fixed per-microbatch compute cost
    (``time.sleep``). Sleeps are immune to CPU contention, which makes
    the measured bubble fraction of a SleepStage pipeline reproduce the
    (P-1)/(M+P-1) schedule theory even on a loaded CI box — the bench
    and bubble tests are built on it."""

    def __init__(self, dim: int, fwd_s: float = 0.02,
                 bwd_s: float | None = None):
        self.in_dim = self.out_dim = int(dim)
        self.fwd_s = float(fwd_s)
        self.bwd_s = float(bwd_s if bwd_s is not None else fwd_s)

    def init_params(self, rng):
        return [np.zeros(1, np.float32)]

    def forward(self, params, x):
        time.sleep(self.fwd_s)
        return x, None

    def backward(self, params, ctx, gy):
        time.sleep(self.bwd_s)
        return gy, [np.zeros(1, np.float32)]


def mse_loss(pred: np.ndarray, target: np.ndarray):
    """Mean-squared error + its gradient w.r.t. pred. Fixed op order —
    both the pipeline's last stage and the reference oracle call exactly
    this."""
    diff = pred - target
    loss = np.float32(np.mean(diff * diff))
    gy = diff * np.float32(2.0 / diff.size)
    return loss, gy


def sgd_update(params: list, grads: list, lr: float, scale: float):
    """In-place ``p -= (lr * scale) * g`` with one fixed multiplier —
    shared by the pipeline loop and the oracle so the float op order is
    identical (scale folds the 1/M microbatch average, and 1/(M*R) when
    a stage is data-parallel)."""
    step = np.float32(lr * scale)
    for p, g in zip(params, grads):
        p -= step * g
    return params


def synth_microbatch(seed: int, step: int, mb: int, batch: int,
                     in_dim: int, out_dim: int):
    """Deterministic synthetic (x, y) for one microbatch — a pure
    function of (seed, step, mb), so every process (and the oracle)
    derives identical bytes without any data movement."""
    rng = np.random.default_rng(
        np.uint64(seed) * np.uint64(1_000_003)
        + np.uint64(step) * np.uint64(1_009) + np.uint64(mb))
    x = rng.standard_normal((batch, in_dim)).astype(np.float32)
    y = rng.standard_normal((batch, out_dim)).astype(np.float32)
    return x, y
