"""Serve public API: @serve.deployment, serve.run, serve.start, ...

Reference: python/ray/serve/api.py (@serve.deployment at :251, serve.run at
:455, serve.start at :56) and serve/_private/deployment_graph_build.py
(bind-tree → deployment list). The controller is a detached named actor in
the "serve" namespace; ``serve.run`` is idempotent per app name (in-place
upgrade of a running app).
"""
from __future__ import annotations

import threading
import time

from ray_tpu.serve._private.constants import (
    CONTROLLER_NAME,
    DEFAULT_APP_NAME,
    PROXY_NAME_PREFIX,
    SERVE_NAMESPACE,
    deployment_id as make_dep_id,
)
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from ray_tpu.serve.handle import (
    DeploymentHandle,
    DeploymentResponse,  # noqa: F401  (re-export)
    _get_controller,
    _shutdown_routers,
)

_lock = threading.RLock()
_proxy_handle = None
_proxy_port = None


class Application:
    """A bound deployment node (the result of ``.bind()``); reference:
    serve's Application / DAGNode for deployment graphs."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs


class Deployment:
    """An undeployed deployment definition (reference: serve/deployment.py
    Deployment). Immutable; ``.options()`` copies."""

    def __init__(self, func_or_class, name: str, config: DeploymentConfig,
                 version: str | None = None):
        self._func_or_class = func_or_class
        self.name = name
        self.config = config
        self.version = version

    def options(self, *, name=None, num_replicas=None, user_config=None,
                max_ongoing_requests=None, max_queued_requests=None,
                autoscaling_config=None,
                ray_actor_options=None, health_check_period_s=None,
                health_check_timeout_s=None, graceful_shutdown_timeout_s=None,
                version=None):
        from dataclasses import replace

        # replace(), not to_dict()/from_dict(): asdict would deep-convert
        # a dataclass user_config into a plain dict (and deep-copy every
        # value), mangling the object the replica's reconfigure expects.
        # The two MUTABLE config fields are copied explicitly so editing
        # the derived deployment never writes through to the original.
        cfg = replace(
            self.config,
            ray_actor_options=dict(self.config.ray_actor_options),
            autoscaling_config=(replace(self.config.autoscaling_config)
                                if self.config.autoscaling_config
                                else None))
        if num_replicas is not None:
            if num_replicas == "auto":
                cfg.autoscaling_config = (cfg.autoscaling_config
                                          or AutoscalingConfig())
            else:
                cfg.num_replicas = int(num_replicas)
        if user_config is not None:
            cfg.user_config = user_config
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = int(max_ongoing_requests)
        if max_queued_requests is not None:
            cfg.max_queued_requests = int(max_queued_requests)
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            cfg.autoscaling_config = autoscaling_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if health_check_timeout_s is not None:
            cfg.health_check_timeout_s = health_check_timeout_s
        if graceful_shutdown_timeout_s is not None:
            cfg.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        # field assignments above bypass validation — re-run it so a bad
        # .options(...) value raises ServeConfigError HERE, not as a
        # deep controller-side failure after deploy
        cfg.__post_init__()
        return Deployment(self._func_or_class, name or self.name, cfg,
                          version or self.version)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)


def deployment(func_or_class=None, *, name=None, num_replicas=None,
               user_config=None, max_ongoing_requests=None,
               max_queued_requests=None,
               autoscaling_config=None, ray_actor_options=None,
               health_check_period_s=None, health_check_timeout_s=None,
               graceful_shutdown_timeout_s=None, version=None):
    """@serve.deployment decorator (reference: serve/api.py:251)."""

    def build(target):
        dep = Deployment(target, name or target.__name__,
                         DeploymentConfig(), version)
        return dep.options(
            num_replicas=num_replicas, user_config=user_config,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            autoscaling_config=autoscaling_config,
            ray_actor_options=ray_actor_options,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s)

    if func_or_class is not None:
        return build(func_or_class)
    return build


# ------------------------------------------------------------------ runtime

def start(http_options: HTTPOptions | dict | None = None, **kwargs):
    """Ensure the Serve instance (controller + HTTP proxy) is running.
    Reference: serve/api.py:56."""
    global _proxy_handle, _proxy_port
    import ray_tpu

    if not ray_tpu.is_initialized():
        ray_tpu.init()
    if http_options is not None and kwargs:
        raise TypeError("pass either http_options or keyword options, "
                        "not both")
    if isinstance(http_options, dict):
        http_options = HTTPOptions(**http_options)
    elif http_options is None:
        http_options = HTTPOptions(**kwargs)
    with _lock:
        from ray_tpu.serve._private.controller import ServeController

        controller = ray_tpu.remote(ServeController).options(
            name=CONTROLLER_NAME, namespace=SERVE_NAMESPACE,
            lifetime="detached", max_concurrency=64, num_cpus=0,
            get_if_exists=True,
        ).remote({"host": http_options.host, "port": http_options.port})
        # start() deliberately serializes under _lock; the gets carry
        # explicit deadlines so a wedged controller fails this caller
        # loudly instead of freezing every serve.* entry point forever
        ray_tpu.get(controller.ready.remote(), timeout=60.0)
        if _proxy_handle is None:
            from ray_tpu.serve._private.proxy import HTTPProxyActor

            opts = ray_tpu.get(controller.get_http_options.remote(),
                               timeout=30.0)
            host = opts.get("host", http_options.host)
            port = opts.get("port", http_options.port)
            # One proxy per node, fixed name: a second driver on the same
            # cluster reuses the detached proxy (and its bound port)
            # instead of colliding on EADDRINUSE (reference: per-node
            # HTTPProxy actors keyed by node, http_state.py).
            node_id = ray_tpu.get_runtime_context().get_node_id()
            _proxy_handle = ray_tpu.remote(HTTPProxyActor).options(
                name=f"{PROXY_NAME_PREFIX}:{node_id}",
                namespace=SERVE_NAMESPACE, lifetime="detached",
                max_concurrency=64, num_cpus=0, get_if_exists=True,
            ).remote(host, port, CONTROLLER_NAME, SERVE_NAMESPACE)
            _proxy_port = ray_tpu.get(_proxy_handle.ready.remote(),
                                      timeout=60.0)
        return controller


def _build_app_spec(target: Application, name: str, route_prefix: str | None,
                    job: str | None = None, job_quota: dict | None = None,
                    job_priority: int | None = None):
    """Flatten the bind tree into deployment specs; nested Application args
    become DeploymentHandles (reference: deployment_graph_build.py)."""
    deployments: dict[str, dict] = {}

    def visit(app: Application) -> DeploymentHandle:
        dep = app._deployment
        if dep.name in deployments:
            # same node object may be bound in several places — reuse
            return DeploymentHandle(dep.name, name)

        def convert(v):
            if isinstance(v, Application):
                return visit(v)
            return v

        # reserve the slot first so diamond graphs don't recurse forever
        deployments[dep.name] = None
        init_args = tuple(convert(a) for a in app._args)
        init_kwargs = {k: convert(v) for k, v in app._kwargs.items()}
        deployments[dep.name] = {
            "name": dep.name,
            "user_callable": dep._func_or_class,
            "init_args": init_args,
            "init_kwargs": init_kwargs,
            "config": dep.config.to_dict(),
            "version": dep.version or "1",
        }
        return DeploymentHandle(dep.name, name)

    visit(target)
    ingress = target._deployment.name
    return {
        "name": name,
        "route_prefix": route_prefix,
        "ingress": ingress,
        "deployments": [d for d in deployments.values() if d],
        "job": job or "",
        "job_quota": job_quota,
        "job_priority": job_priority,
    }


def run(target: Application, *, name: str = DEFAULT_APP_NAME,
        route_prefix: str | None = "/", blocking: bool = False,
        job: str | None = None, job_quota: dict | None = None,
        job_priority: int | None = None,
        _timeout_s: float = 60.0) -> DeploymentHandle:
    """Deploy an application and wait until healthy (reference:
    serve/api.py:455).

    ``job`` makes the app a first-class TENANT of the multi-tenant
    scheduling plane (``ray_tpu.util.jobs``): the controller registers
    the job with ``job_quota``/``job_priority`` (idempotent — ``None``
    keeps existing policy) and backs every replica with a job-labeled
    capacity placement group named by its slot tag. A traffic spike on a
    high-priority app then claims capacity THROUGH the plane — up to and
    including preempting a lower-priority training gang — and scale-down
    drains replicas through the preemption-warning machinery, returning
    the capacity when the spike passes."""
    import ray_tpu

    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(f"serve.run expects an Application (from .bind()), "
                        f"got {type(target)}")
    controller = start()
    spec = _build_app_spec(target, name, route_prefix,
                           job, job_quota, job_priority)
    ray_tpu.get(controller.deploy_application.remote(spec))
    # wait for the app to report RUNNING
    deadline = time.monotonic() + _timeout_s
    while time.monotonic() < deadline:
        status = ray_tpu.get(controller.get_app_status.remote(name))
        app = status.get(name)
        if app and app["status"] == "RUNNING":
            break
        time.sleep(0.05)
    else:
        raise TimeoutError(
            f"app {name!r} did not become RUNNING within {_timeout_s}s: "
            f"{ray_tpu.get(controller.get_app_status.remote(name))}")
    handle = DeploymentHandle(spec["ingress"], name)
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return handle


def status() -> dict:
    import ray_tpu

    controller = _get_controller()
    return ray_tpu.get(controller.get_app_status.remote())


def delete(name: str):
    import ray_tpu

    controller = _get_controller()
    ray_tpu.get(controller.delete_application.remote(name))


def get_app_handle(name: str = DEFAULT_APP_NAME) -> DeploymentHandle:
    import ray_tpu

    controller = _get_controller()
    apps = ray_tpu.get(controller.get_app_status.remote(name))
    if name not in apps:
        raise ValueError(f"no Serve app named {name!r}")
    ingress = apps[name]["ingress"]
    return DeploymentHandle(ingress.split("#", 1)[1], name)


def get_deployment_handle(deployment_name: str,
                          app_name: str = DEFAULT_APP_NAME
                          ) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def http_port() -> int | None:
    """The port the local HTTP proxy bound (useful with port=0 in tests)."""
    return _proxy_port


def shutdown():
    """Tear down the Serve instance (reference: serve/api.py serve.shutdown)."""
    global _proxy_handle, _proxy_port
    import ray_tpu

    with _lock:
        _shutdown_routers()
        # the proxy is a DETACHED named actor: resolve it by name, not
        # only through this process's handle — `ray-tpu serve shutdown`
        # runs in a fresh process where _proxy_handle is None, and
        # leaking the proxy would leave its port bound serving stale
        # routes
        proxies = [_proxy_handle] if _proxy_handle is not None else []
        if not proxies:
            try:
                import ray_tpu.util as _util

                for row in _util.list_named_actors(all_namespaces=True):
                    if (row.get("namespace") == SERVE_NAMESPACE
                            and str(row.get("name", "")).startswith(
                                PROXY_NAME_PREFIX)):
                        try:
                            # shutdown serializes against start() under
                            # _lock by design; the lookup is bounded by
                            # the GCS RPC deadline
                            proxies.append(ray_tpu.get_actor(  # raylint: disable=RTL101
                                row["name"],
                                namespace=SERVE_NAMESPACE))
                        except ValueError:
                            pass
            except Exception:
                pass
        for proxy in proxies:
            try:
                ray_tpu.get(proxy.shutdown.remote(), timeout=5.0)
                ray_tpu.kill(proxy)
            except Exception:
                pass
        _proxy_handle = None
        _proxy_port = None
        try:
            controller = _get_controller()
        except ValueError:
            return
        try:
            ray_tpu.get(controller.graceful_shutdown.remote(), timeout=15.0)
        except Exception:
            pass
        try:
            ray_tpu.kill(controller)
        except Exception:
            pass
