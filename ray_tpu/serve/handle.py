"""DeploymentHandle — Python-native calls into a deployment.

Reference: python/ray/serve/handle.py (DeploymentHandle / ServeHandle →
Router → ReplicaSet). A handle owns (a process-wide cached) Router for its
deployment; ``.remote()`` returns a DeploymentResponse whose ``.result()``
blocks on the replica call. Responses can be passed as arguments to other
handle calls (model composition) — they are converted to the underlying
ObjectRef, which the runtime resolves at execution time.
"""
from __future__ import annotations

import threading

from ray_tpu.serve._private.constants import (
    CONTROLLER_NAME,
    SERVE_NAMESPACE,
)

_routers_lock = threading.Lock()
_routers: dict[str, object] = {}


def _get_controller():
    import ray_tpu

    return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)


def _get_router(deployment_id: str):
    from ray_tpu.serve._private.router import Router

    with _routers_lock:
        router = _routers.get(deployment_id)
        if router is None:
            import ray_tpu

            controller = _get_controller()
            info = ray_tpu.get(
                controller.get_deployment_info.remote(deployment_id))
            cap = (info or {}).get("max_ongoing_requests", 8)
            router = Router(controller, deployment_id,
                            max_ongoing_requests=cap)
            _routers[deployment_id] = router
        return router


def _shutdown_routers():
    with _routers_lock:
        for r in _routers.values():
            r.stop()
        _routers.clear()


class DeploymentResponse:
    """Future-like result of a handle call (reference: handle.py
    DeploymentResponse). Submits eagerly; ``result()`` transparently
    retries on another replica if the chosen one died (the reference's
    replica scheduler does the same for actor-died failures)."""

    MAX_REPLICA_RETRIES = 3

    def __init__(self, router, method_name, args, kwargs):
        self._router = router
        self._method_name = method_name
        self._args = args
        self._kwargs = kwargs
        self._ref, self._replica_id = router.assign_request(
            method_name, args, kwargs)

    def result(self, timeout_s: float | None = None):
        import time

        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError

        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)

        def remaining():
            return (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))

        for attempt in range(self.MAX_REPLICA_RETRIES + 1):
            try:
                result = ray_tpu.get(self._ref, timeout=remaining())
                if isinstance(result, dict) and "__serve_stream__" in result:
                    # streaming deployment: hand back an iterator pulling
                    # chunks from the replica (HTTP callers get chunked
                    # transfer encoding via the proxy instead)
                    return _StreamChunkIterator(result)
                return result
            except ActorDiedError:
                self._router.mark_replica_dead(self._replica_id)
                if attempt == self.MAX_REPLICA_RETRIES:
                    raise
                left = remaining()   # re-read: the failed get consumed time
                self._ref, self._replica_id = self._router.assign_request(
                    self._method_name, self._args, self._kwargs,
                    timeout_s=left if left is not None else 30.0)

    def _to_object_ref(self):
        return self._ref


class _StreamChunkIterator:
    """Iterates a replica-held streaming response chunk by chunk (the
    handle-call analog of the proxy's chunked-transfer relay)."""

    def __init__(self, marker: dict):
        import ray_tpu

        self._sid = marker["__serve_stream__"]
        self._actor = ray_tpu.get_actor(marker["replica_actor"],
                                        namespace="serve")
        self.status_code = marker.get("status", 200)
        self.content_type = marker.get("content_type")
        self.headers = marker.get("headers") or {}
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        import ray_tpu
        from ray_tpu.serve._private.constants import stream_chunk_timeout_s

        while not self._done:
            chunks, done = ray_tpu.get(
                self._actor.stream_next.remote(self._sid),
                timeout=stream_chunk_timeout_s())
            self._done = done
            if chunks:
                return chunks[0]
        raise StopIteration

    def cancel(self):
        self._done = True
        try:
            self._actor.stream_cancel.remote(self._sid)
        except Exception:
            pass


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str,
                 method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name

    @property
    def _deployment_id(self):
        from ray_tpu.serve._private.constants import deployment_id

        return deployment_id(self.app_name, self.deployment_name)

    def options(self, *, method_name: str | None = None):
        return DeploymentHandle(self.deployment_name, self.app_name,
                                method_name or self._method_name)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self.app_name, name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        args = tuple(a._to_object_ref()
                     if isinstance(a, DeploymentResponse) else a
                     for a in args)
        kwargs = {k: (v._to_object_ref()
                      if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        router = _get_router(self._deployment_id)
        return DeploymentResponse(router, self._method_name, args, kwargs)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method_name))

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}#{self.deployment_name}"
                f".{self._method_name})")
