"""DeploymentHandle — Python-native calls into a deployment.

Reference: python/ray/serve/handle.py (DeploymentHandle / ServeHandle →
Router → ReplicaSet). A handle owns (a process-wide cached) Router for its
deployment; ``.remote()`` returns a DeploymentResponse whose ``.result()``
blocks on the replica call. Responses can be passed as arguments to other
handle calls (model composition) — they are converted to the underlying
ObjectRef, which the runtime resolves at execution time.
"""
from __future__ import annotations

import threading

from ray_tpu.serve._private.constants import (
    CONTROLLER_NAME,
    SERVE_NAMESPACE,
)

_routers_lock = threading.Lock()
_routers: dict[str, object] = {}
# bumped by _shutdown_routers: an install whose build straddled a sweep
# must not re-populate the dict with a live (thread-owning) router
_routers_gen = 0


def _get_controller():
    import ray_tpu

    return ray_tpu.get_actor(CONTROLLER_NAME, namespace=SERVE_NAMESPACE)


def _get_router(deployment_id: str):
    from ray_tpu.serve._private.router import Router

    # Build OUTSIDE the lock: construction is a controller lookup + a
    # GCS round trip, and holding the module lock across it serialized
    # every first call to every OTHER deployment behind one slow
    # controller (raylint RTL101 — the shared_weights deadlock class).
    # The install re-checks under the lock: a racing builder's loser is
    # stopped, and a build that straddled a _shutdown_routers sweep
    # (generation changed) is stopped and retried instead of installed
    # — post-shutdown the retry fails at the controller lookup, which
    # is the honest error.
    while True:
        with _routers_lock:
            router = _routers.get(deployment_id)
            gen = _routers_gen
        if router is not None:
            return router
        import ray_tpu

        controller = _get_controller()
        info = ray_tpu.get(
            controller.get_deployment_info.remote(deployment_id),
            timeout=30.0)
        cap = (info or {}).get("max_ongoing_requests", 8)
        queued_cap = (info or {}).get("max_queued_requests", 32)
        router = Router(controller, deployment_id,
                        max_ongoing_requests=cap,
                        max_queued_requests=queued_cap)
        with _routers_lock:
            if _routers_gen == gen:
                winner = _routers.setdefault(deployment_id, router)
            else:
                winner = None   # swept mid-build: don't resurrect
        if winner is router:
            return winner
        router.stop()   # lost the race / swept: ours has threads
        if winner is not None:
            return winner


def _shutdown_routers():
    global _routers_gen
    with _routers_lock:
        _routers_gen += 1
        for r in _routers.values():
            r.stop()
        _routers.clear()


class DeploymentResponse:
    """Future-like result of a handle call (reference: handle.py
    DeploymentResponse). Submits eagerly; ``result()`` transparently
    retries on another replica if the chosen one died or started
    draining (the reference's replica scheduler does the same for
    actor-died failures).

    Failover latency: when the router has a GCS death watch, ``result()``
    waits in short slices and checks the router's death flag between
    them, so a replica killed mid-request fails over within ~the death
    feed's publish latency (milliseconds-to-sub-second) instead of
    waiting for the object layer to surface ``ActorDiedError``."""

    MAX_REPLICA_RETRIES = 3

    def __init__(self, router, method_name, args, kwargs):
        self._router = router
        self._method_name = method_name
        self._args = args
        self._kwargs = kwargs
        import time

        # stamped BEFORE assign_request: the router's bounded-queue wait
        # happens inside it, and the latency histogram is cataloged as
        # router queueing + execution — exactly the overload signal
        self._start = time.monotonic()
        # public: how many times this request was re-dispatched to
        # another replica (death/drain failover) — 0 on the happy path.
        # Lets callers and benches attribute tail latency to failover.
        self.num_failovers = 0
        # settled outcome, replayed by repeat result() calls (metrics
        # and retries must run once per REQUEST, not once per call)
        self._done = False
        self._value = None
        self._error: BaseException | None = None
        self._ref, self._replica_id = router.assign_request(
            method_name, args, kwargs)

    def _get(self, remaining):
        """One attempt against the currently-assigned replica. Raises
        ActorDiedError as soon as the router's death feed flags the
        replica — without this, a killed replica's in-flight request
        waits on the object layer's own (slower) death propagation.

        The get is attempted BEFORE the death flag is consulted: a
        replica that died just after completing the request leaves a
        perfectly good result in the object store, and re-executing it
        on a survivor would double the side effects and the latency.

        The short-timeout re-entry loop is a deliberate tradeoff vs the
        WorkerGroup waiter-thread pattern (PR 5): serve requests are
        typically short (one-few polls total), a thread per request is
        worse at serving QPS, and for a long-running request the ≲1 Hz
        re-entries cost milliseconds against its multi-second body."""
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError, GetTimeoutError

        if not self._router.has_death_watch():
            return ray_tpu.get(self._ref, timeout=remaining())
        poll = 0.05
        while True:
            left = remaining()
            try:
                return ray_tpu.get(self._ref,
                                   timeout=(poll if left is None
                                            else min(poll, left)))
            except GetTimeoutError:
                if self._router.replica_dead(self._replica_id):
                    raise ActorDiedError(
                        "", f"replica {self._replica_id} flagged dead by "
                            f"the router death feed") from None
                if left is not None and left <= poll:
                    raise
                poll = min(poll * 2, 1.0)   # escalate: cheap for short
                #                             requests, low overhead for long

    def result(self, timeout_s: float | None = None):
        if self._done:
            # replay the settled outcome: metrics/retries ran once
            if self._error is not None:
                raise self._error
            return self._value
        try:
            self._value = self._result_once(timeout_s)
            self._done = True
            return self._value
        except BaseException as e:
            # timeouts are NOT settled (the caller may retry with more
            # budget); terminal errors are
            from ray_tpu.exceptions import GetTimeoutError

            if not isinstance(e, (GetTimeoutError, TimeoutError)):
                self._error = e
                self._done = True
            raise

    def _result_once(self, timeout_s: float | None):
        import time

        import ray_tpu  # noqa: F401  (runtime must be initialized)
        from ray_tpu._private import telemetry as _tm
        from ray_tpu.exceptions import (
            ActorDiedError,
            ReplicaDrainingError,
            TaskError,
        )

        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)

        def remaining():
            return (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))

        dep = self._router._deployment_id
        for attempt in range(self.MAX_REPLICA_RETRIES + 1):
            try:
                result = self._get(remaining)
                _tm.observe("ray_tpu_serve_request_latency_seconds",
                            time.monotonic() - self._start,
                            tags={"deployment": dep})
                _tm.counter_inc("ray_tpu_serve_requests_total",
                                tags={"deployment": dep, "result": "ok"})
                if isinstance(result, dict) and "__serve_stream__" in result:
                    # streaming deployment: hand back an iterator pulling
                    # chunks from the replica (HTTP callers get chunked
                    # transfer encoding via the proxy instead)
                    return _StreamChunkIterator(result)
                return result
            except ActorDiedError:
                self._router.mark_replica_dead(self._replica_id)
                if attempt == self.MAX_REPLICA_RETRIES:
                    _tm.counter_inc("ray_tpu_serve_requests_total",
                                    tags={"deployment": dep,
                                          "result": "error"})
                    raise
            except (ReplicaDrainingError, TaskError) as e:
                # a draining replica refuses the request with a typed
                # error: re-dispatch to a survivor (scale-down must not
                # lose accepted requests that raced the routing update).
                # RayError subclasses ship UNWRAPPED (serialize_error),
                # so the drain error arrives as itself — the TaskError
                # arm only covers transports that wrap it anyway.
                draining = isinstance(e, ReplicaDrainingError) or \
                    getattr(e, "cause_cls_name", None) == \
                    "ReplicaDrainingError"
                if not draining or attempt == self.MAX_REPLICA_RETRIES:
                    _tm.counter_inc("ray_tpu_serve_requests_total",
                                    tags={"deployment": dep,
                                          "result": "error"})
                    raise
                # drop the drainer from selection: it rejects instantly
                # (in_flight ~0), so p2c would re-pick it every retry
                # until the controller's broadcast lands
                self._router.mark_replica_draining(self._replica_id)
                _tm.counter_inc("ray_tpu_serve_failovers_total",
                                tags={"deployment": dep})
            left = remaining()   # re-read: the failed get consumed time
            self.num_failovers += 1
            self._ref, self._replica_id = self._router.assign_request(
                self._method_name, self._args, self._kwargs,
                timeout_s=left if left is not None else 30.0)

    def _to_object_ref(self):
        return self._ref


class _StreamChunkIterator:
    """Iterates a replica-held streaming response chunk by chunk (the
    handle-call analog of the proxy's chunked-transfer relay)."""

    def __init__(self, marker: dict):
        import ray_tpu

        self._sid = marker["__serve_stream__"]
        self._actor = ray_tpu.get_actor(marker["replica_actor"],
                                        namespace="serve")
        self.status_code = marker.get("status", 200)
        self.content_type = marker.get("content_type")
        self.headers = marker.get("headers") or {}
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> bytes:
        import ray_tpu
        from ray_tpu.serve._private.constants import stream_chunk_timeout_s

        while not self._done:
            chunks, done = ray_tpu.get(
                self._actor.stream_next.remote(self._sid),
                timeout=stream_chunk_timeout_s())
            self._done = done
            if chunks:
                return chunks[0]
        raise StopIteration

    def cancel(self):
        self._done = True
        try:
            self._actor.stream_cancel.remote(self._sid)
        except Exception:
            pass


class DeploymentHandle:
    def __init__(self, deployment_name: str, app_name: str,
                 method_name: str = "__call__"):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._method_name = method_name

    @property
    def _deployment_id(self):
        from ray_tpu.serve._private.constants import deployment_id

        return deployment_id(self.app_name, self.deployment_name)

    def options(self, *, method_name: str | None = None):
        return DeploymentHandle(self.deployment_name, self.app_name,
                                method_name or self._method_name)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.deployment_name, self.app_name, name)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        args = tuple(a._to_object_ref()
                     if isinstance(a, DeploymentResponse) else a
                     for a in args)
        kwargs = {k: (v._to_object_ref()
                      if isinstance(v, DeploymentResponse) else v)
                  for k, v in kwargs.items()}
        router = _get_router(self._deployment_id)
        return DeploymentResponse(router, self._method_name, args, kwargs)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._method_name))

    def __repr__(self):
        return (f"DeploymentHandle({self.app_name}#{self.deployment_name}"
                f".{self._method_name})")
