"""Serve configuration dataclasses.

Reference parity: python/ray/serve/config.py (DeploymentConfig,
AutoscalingConfig, HTTPOptions). Plain dataclasses here — the reference uses
pydantic for REST-facing validation; our REST surface is the JSON status
endpoint only, so stdlib dataclasses keep the dependency surface zero.
"""
from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class AutoscalingConfig:
    """Replica autoscaling policy inputs.

    Reference: python/ray/serve/config.py AutoscalingConfig and
    serve/_private/autoscaling_policy.py. The controller scales the number
    of replicas so that (total ongoing requests / replicas) tracks
    ``target_ongoing_requests``, with hysteresis via the up/downscale delays.
    """
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    metrics_interval_s: float = 0.2
    # Fraction of the gap between current and desired replicas applied per
    # decision (1.0 = jump straight to desired).
    smoothing_factor: float = 1.0

    def desired_replicas(self, current: int, total_ongoing: float) -> int:
        if current == 0:
            return self.min_replicas
        error_ratio = (total_ongoing / current) / self.target_ongoing_requests
        desired = current * error_ratio
        if self.smoothing_factor != 1.0:
            desired = current + (desired - current) * self.smoothing_factor
        import math

        desired = math.ceil(desired - 1e-9)
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclass
class DeploymentConfig:
    """Per-deployment behavior knobs.

    Reference: serve/config.py DeploymentConfig (num_replicas,
    max_ongoing_requests nee max_concurrent_queries, user_config,
    graceful_shutdown, health checks).
    """
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    user_config: object = None
    graceful_shutdown_timeout_s: float = 5.0
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 5.0
    autoscaling_config: AutoscalingConfig | None = None
    ray_actor_options: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        from dataclasses import asdict

        d = asdict(self)
        if self.autoscaling_config is not None:
            d["autoscaling_config"] = asdict(self.autoscaling_config)
        return d

    @classmethod
    def from_dict(cls, d: dict) -> "DeploymentConfig":
        d = dict(d)
        ac = d.get("autoscaling_config")
        if isinstance(ac, dict):
            d["autoscaling_config"] = AutoscalingConfig(**ac)
        return cls(**d)


@dataclass
class HTTPOptions:
    """Reference: serve/config.py HTTPOptions (host/port/root_path)."""
    host: str = "127.0.0.1"
    port: int = 8000
    root_path: str = ""
