"""Serve configuration dataclasses.

Reference parity: python/ray/serve/config.py (DeploymentConfig,
AutoscalingConfig, HTTPOptions). Plain dataclasses here — the reference uses
pydantic for REST-facing validation; our REST surface is the JSON status
endpoint only, so stdlib dataclasses keep the dependency surface zero.
Validation happens in ``__post_init__`` instead (the pydantic analog):
bad values raise a named ``ServeConfigError`` at construction, where the
operator wrote them, not as a deep runtime failure three actors later.
"""
from __future__ import annotations

from dataclasses import dataclass, field

from ray_tpu.exceptions import ServeConfigError


def _require(cond: bool, message: str):
    if not cond:
        raise ServeConfigError(message)


@dataclass
class AutoscalingConfig:
    """Replica autoscaling policy inputs.

    Reference: python/ray/serve/config.py AutoscalingConfig and
    serve/_private/autoscaling_policy.py. The controller scales the number
    of replicas so that (total ongoing requests / replicas) tracks
    ``target_ongoing_requests``, with hysteresis via the up/downscale delays.
    """
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 0.5
    downscale_delay_s: float = 2.0
    metrics_interval_s: float = 0.2
    # Fraction of the gap between current and desired replicas applied per
    # decision (1.0 = jump straight to desired).
    smoothing_factor: float = 1.0

    def __post_init__(self):
        _require(self.min_replicas >= 0,
                 f"min_replicas must be >= 0, got {self.min_replicas}")
        _require(self.max_replicas >= 1,
                 f"max_replicas must be >= 1, got {self.max_replicas}")
        _require(self.min_replicas <= self.max_replicas,
                 f"min_replicas ({self.min_replicas}) must not exceed "
                 f"max_replicas ({self.max_replicas})")
        _require(self.target_ongoing_requests > 0,
                 f"target_ongoing_requests must be > 0, got "
                 f"{self.target_ongoing_requests}")
        for name in ("upscale_delay_s", "downscale_delay_s",
                     "metrics_interval_s"):
            _require(getattr(self, name) >= 0,
                     f"{name} must be >= 0, got {getattr(self, name)}")
        _require(self.smoothing_factor > 0,
                 f"smoothing_factor must be > 0, got "
                 f"{self.smoothing_factor}")

    def desired_replicas(self, current: int, total_ongoing: float) -> int:
        if current == 0:
            return self.min_replicas
        error_ratio = (total_ongoing / current) / self.target_ongoing_requests
        desired = current * error_ratio
        if self.smoothing_factor != 1.0:
            desired = current + (desired - current) * self.smoothing_factor
        import math

        desired = math.ceil(desired - 1e-9)
        return max(self.min_replicas, min(self.max_replicas, desired))


@dataclass
class DeploymentConfig:
    """Per-deployment behavior knobs.

    Reference: serve/config.py DeploymentConfig (num_replicas,
    max_ongoing_requests nee max_concurrent_queries, user_config,
    graceful_shutdown, health checks). ``max_queued_requests`` bounds the
    router-side wait queue PER REPLICA: once every replica is at
    ``max_ongoing_requests`` and ``max_queued_requests * num_replicas``
    callers are already waiting, further requests are shed with
    ``ServeOverloadedError`` instead of queuing without bound.
    """
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    max_queued_requests: int = 32
    user_config: object = None
    graceful_shutdown_timeout_s: float = 5.0
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 5.0
    autoscaling_config: AutoscalingConfig | None = None
    ray_actor_options: dict = field(default_factory=dict)

    def __post_init__(self):
        _require(self.num_replicas >= 1,
                 f"num_replicas must be >= 1, got {self.num_replicas}")
        _require(self.max_ongoing_requests >= 1,
                 f"max_ongoing_requests must be >= 1, got "
                 f"{self.max_ongoing_requests}")
        _require(self.max_queued_requests >= 0,
                 f"max_queued_requests must be >= 0, got "
                 f"{self.max_queued_requests}")
        for name in ("graceful_shutdown_timeout_s", "health_check_period_s",
                     "health_check_timeout_s"):
            _require(getattr(self, name) >= 0,
                     f"{name} must be >= 0, got {getattr(self, name)}")

    def to_dict(self) -> dict:
        from dataclasses import asdict

        # field-by-field, NOT asdict(self): user_config is OPAQUE user
        # data — asdict would recursively convert any dataclass inside
        # it to a plain dict and deep-copy every value (crashing on
        # un-deepcopy-able values like locks/handles, paying a full copy
        # of large weight pytrees), mangling what the replica's
        # reconfigure receives
        return {
            "num_replicas": self.num_replicas,
            "max_ongoing_requests": self.max_ongoing_requests,
            "max_queued_requests": self.max_queued_requests,
            "user_config": self.user_config,
            "graceful_shutdown_timeout_s": self.graceful_shutdown_timeout_s,
            "health_check_period_s": self.health_check_period_s,
            "health_check_timeout_s": self.health_check_timeout_s,
            "autoscaling_config": (asdict(self.autoscaling_config)
                                   if self.autoscaling_config is not None
                                   else None),
            "ray_actor_options": dict(self.ray_actor_options),
        }

    @classmethod
    def from_dict(cls, d: dict) -> "DeploymentConfig":
        d = dict(d)
        ac = d.get("autoscaling_config")
        if isinstance(ac, dict):
            d["autoscaling_config"] = AutoscalingConfig(**ac)
        return cls(**d)


@dataclass
class HTTPOptions:
    """Reference: serve/config.py HTTPOptions (host/port/root_path)."""
    host: str = "127.0.0.1"
    port: int = 8000
    root_path: str = ""
