"""Long-poll config push: controller → routers/proxies.

Reference: python/ray/serve/_private/long_poll.py (LongPollHost,
LongPollClient at :67). The host side lives inside the controller actor;
clients issue a blocking ``listen_for_change`` actor call carrying the
versions they have seen, and the call returns only when some key advances
(or a timeout passes, so clients can detect a dead controller). This is the
same push-on-change design as the reference, carried over our actor RPC
instead of gRPC.
"""
from __future__ import annotations

import threading


LISTEN_TIMEOUT_S = 10.0


class LongPollHost:
    """State holder + condition variable. Embedded in ServeController."""

    def __init__(self):
        self._lock = threading.Condition()
        self._values: dict[str, object] = {}
        self._versions: dict[str, int] = {}

    def notify_changed(self, key: str, value) -> None:
        with self._lock:
            self._values[key] = value
            self._versions[key] = self._versions.get(key, 0) + 1
            self._lock.notify_all()

    def drop_key(self, key: str) -> None:
        with self._lock:
            self._values.pop(key, None)
            self._versions[key] = self._versions.get(key, 0) + 1
            self._lock.notify_all()

    def listen_for_change(self, snapshot_ids: dict[str, int],
                          timeout_s: float = LISTEN_TIMEOUT_S) -> dict:
        """Block until any key in snapshot_ids has a newer version than the
        caller has seen (version -1 = "send me whatever exists"). Returns
        {key: (version, value)} for changed keys; {} on timeout."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._lock:
            while True:
                updates = {}
                for key, seen in snapshot_ids.items():
                    cur = self._versions.get(key, 0)
                    if cur > seen and key in self._values:
                        updates[key] = (cur, self._values[key])
                    elif cur > seen and key not in self._values:
                        # key dropped — tell the client so it stops caching
                        updates[key] = (cur, None)
                if updates:
                    return updates
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                self._lock.wait(remaining)


class LongPollClient:
    """Background thread repeatedly long-polling the controller.

    callbacks: {key: fn(value)} invoked (on the poll thread) each time the
    key's value changes.
    """

    def __init__(self, controller_handle, callbacks: dict):
        self._controller = controller_handle
        self._callbacks = dict(callbacks)
        self._snapshot_ids = {key: -1 for key in self._callbacks}
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-long-poll")
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def _loop(self):
        import ray_tpu

        while not self._stopped.is_set():
            try:
                ref = self._controller.listen_for_change.remote(
                    self._snapshot_ids)
                updates = ray_tpu.get(ref, timeout=LISTEN_TIMEOUT_S + 5.0)
            except Exception:
                if self._stopped.is_set():
                    return
                # controller restarting / transient RPC failure — back off
                self._stopped.wait(0.5)
                continue
            for key, (version, value) in (updates or {}).items():
                self._snapshot_ids[key] = version
                cb = self._callbacks.get(key)
                if cb is not None:
                    try:
                        cb(value)
                    except Exception:
                        pass
