"""Zero-copy same-node model-weight sharing for Serve replicas.

N replicas of one model on one node would naively hold N host copies of
the weights (before device transfer). This module keeps ONE copy in the
node's shared-memory object store: the first replica to ask runs the
loader and publishes the arrays through the store's ``put_ephemeral``
path (the PR 4 primitive: no spill probe, never hits disk); every later
replica maps the sealed segment zero-copy (``StoreClient.get`` returns a
pinned view) and rebuilds its arrays as read-only ``np.frombuffer`` views
over the shared bytes — load time and N-1 copies both disappear.

Keying: the object id is content-addressed from the caller's key (use
``f"{deployment}:{version}"`` so a redeploy with new weights mints a new
segment). A leftover segment from a crashed prior run with the same key
therefore holds the SAME bytes and is safe to reuse — which is exactly
why ``get``-before-``load`` is correct here where it wouldn't be for the
collective plane's per-message ids.

Lifetime: mapped views pin the segment; ``release_shared_weights``
drops this process's pin and (best-effort) deletes the store object.
Replicas that exit simply drop their pins with the process. The store is
node-local and dies with the node, so an unreleased segment is bounded
by (models served on the node), not by traffic.

No worker runtime / store full → the loader's private copy is returned
(correct, just not shared); sharing is an optimization, never a
requirement.
"""
from __future__ import annotations

import hashlib
import pickle
import struct
import threading

_ALIGN = 64            # buffer offsets aligned for vectorized consumers
# _lock guards the two dicts ONLY — never held across loader()/store IO:
# a weights load can take minutes, replicas serve on many threads, and a
# loader that composes another shared_weights(key2) call must not
# deadlock on a process-global lock
_lock = threading.Lock()
# key → (value, pin|None): keeps the pinned mapping (and its views) alive
# for this process and makes repeat calls O(1)
_cache: dict[str, tuple] = {}
# key → Event: de-dups concurrent same-key loads within this process
_inflight: dict[str, threading.Event] = {}


class _ArrayRef:
    """Skeleton placeholder for one stripped array (picklable)."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index

    def __reduce__(self):
        return (_ArrayRef, (self.index,))


def _object_id(key: str) -> bytes:
    return hashlib.sha256(b"serve-weights:" + key.encode()).digest()[:16]


def _strip_arrays(obj, specs: list, buffers: list):
    """Replace every ndarray in a dict/list/tuple pytree with an
    _ArrayRef; record (shape, dtype) and the contiguous buffer."""
    import numpy as np

    if isinstance(obj, np.ndarray):
        arr = np.ascontiguousarray(obj)
        specs.append((arr.shape, arr.dtype.str))
        buffers.append(arr)
        return _ArrayRef(len(specs) - 1)
    if isinstance(obj, dict):
        return {k: _strip_arrays(v, specs, buffers) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_strip_arrays(v, specs, buffers) for v in obj)
    return obj


def _fill_arrays(obj, arrays: list):
    if isinstance(obj, _ArrayRef):
        return arrays[obj.index]
    if isinstance(obj, dict):
        return {k: _fill_arrays(v, arrays) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_fill_arrays(v, arrays) for v in obj)
    return obj


def _serialize(value) -> list:
    """[u64 header_len][pickle(skeleton, specs)][pad][buf0][pad][buf1]...
    Buffers are appended as views (scatter-gather into the store's
    create()d segment — no intermediate concatenation)."""
    import numpy as np

    specs: list = []
    buffers: list = []
    skeleton = _strip_arrays(value, specs, buffers)
    header = pickle.dumps((skeleton, specs), protocol=5)
    parts: list = [struct.pack("<Q", len(header)), header]
    offset = 8 + len(header)
    for arr in buffers:
        pad = (-offset) % _ALIGN
        if pad:
            parts.append(b"\x00" * pad)
            offset += pad
        view = memoryview(np.ascontiguousarray(arr)).cast("B")
        parts.append(view)
        offset += len(view)
    return parts


def _deserialize(mv: memoryview):
    """Rebuild the value with arrays as READ-ONLY views over ``mv`` (the
    pinned shm segment) — this is the zero-copy step."""
    import numpy as np

    mv = mv.toreadonly()
    (header_len,) = struct.unpack("<Q", mv[:8])
    skeleton, specs = pickle.loads(mv[8:8 + header_len])
    arrays = []
    offset = 8 + header_len
    for shape, dtype_str in specs:
        offset += (-offset) % _ALIGN
        dtype = np.dtype(dtype_str)
        count = 1
        for d in shape:
            count *= d
        arr = np.frombuffer(mv, dtype=dtype, count=count,
                            offset=offset).reshape(shape)
        arrays.append(arr)
        offset += count * dtype.itemsize
    return _fill_arrays(skeleton, arrays)


def shared_weights(key: str, loader):
    """Load-once-per-node weights. ``loader()`` must return a pytree
    (dict/list/tuple nesting) of numpy arrays plus picklable scalars;
    the returned arrays are READ-ONLY views over node-shared memory
    (copy before mutating — serving weights shouldn't be mutated).

    Typical replica usage::

        class Model:
            def __init__(self):
                w = serve.shared_weights("mymodel:v3", load_from_disk)
                self.params = jax.device_put(w)   # shm → HBM, no 2nd
                #                                   host copy ever existed
    """
    while True:
        with _lock:
            hit = _cache.get(key)
            if hit is not None:
                return hit[0]
            ev = _inflight.get(key)
            if ev is None:
                _inflight[key] = threading.Event()
                break           # this thread owns the load
        ev.wait()               # another thread is loading this key
    try:
        entry = _load_entry(key, loader)
        with _lock:
            _cache[key] = entry
        return entry[0]
    finally:
        with _lock:
            ev = _inflight.pop(key, None)
        if ev is not None:
            ev.set()


def _load_entry(key: str, loader) -> tuple:
    """One (value, pin|None) load — runs WITHOUT the module lock."""
    worker = _current_worker()
    store = getattr(worker, "store", None) if worker else None
    if store is None:
        return (loader(), None)
    oid = _object_id(key)
    pin = _safe_get(store, oid)
    if pin is None:
        value = loader()
        try:
            from ray_tpu._private import memory_anatomy as _ma

            with _ma.tagged("serve_weights", group=key):
                pin = _publish_or_adopt(store, oid, _serialize(value))
        except Exception:
            pin = None   # store full / unpicklable → private copy
        if pin is None:
            return (value, None)
    try:
        value = _deserialize(pin.memoryview())
    except Exception:
        # stranded segment with a garbage layout (e.g. key collision
        # with foreign bytes): fall back to a private load
        pin.release()
        return (loader(), None)
    return (value, pin)


def release_shared_weights(key: str, delete: bool = False):
    """Drop this process's pin (views into the segment become invalid —
    only call once the model is done with them). ``delete=True`` also
    removes the store object so the node reclaims the memory once every
    other pin is gone."""
    with _lock:
        entry = _cache.pop(key, None)
    if entry is None:
        return False
    pin = entry[1]
    if pin is not None:
        try:
            pin.release()
        except Exception:
            pass
    if delete:
        worker = _current_worker()
        store = getattr(worker, "store", None) if worker else None
        if store is not None:
            try:
                store.delete_ephemeral(_object_id(key))
            except Exception:
                pass
    return True


def _publish_or_adopt(store, oid: bytes, parts: list):
    """Create-if-absent publish. NOT ``put_ephemeral``: that primitive's
    EXISTS handling deletes the existing object and recreates it —
    correct for the collective plane's per-message ids (an existing id
    is always a stranded leftover) but wrong here, where ids are stable
    and content-addressed: with N replicas starting concurrently, the
    loser of the publish race would delete the winner's LIVE segment out
    from under its pinned zero-copy views. Same key = same bytes, so the
    loser simply ADOPTS the winner's segment instead."""
    views = [memoryview(p).cast("B") for p in parts]
    total = sum(len(v) for v in views)
    buf = store.create(oid, total)
    if buf is None:
        # lost the race (or a same-key leftover from a prior run —
        # identical bytes either way): map the existing segment. A None
        # get here means the winner hasn't sealed yet; the caller falls
        # back to its private copy rather than spin.
        return _safe_get(store, oid)
    try:
        dst = memoryview(buf).cast("B")
        off = 0
        for v in views:
            dst[off:off + len(v)] = v
            off += len(v)
        store.seal(oid)
        # raw create+seal bypasses put_parts' ledger hook — record the
        # publish here so the segment carries serve_weights provenance
        # (the caller's tagged() context is active)
        from ray_tpu._private import memory_anatomy as _ma
        from ray_tpu._private import telemetry as _tm

        if _tm.ENABLED:
            _ma.LEDGER.note_put(oid, total)
    except BaseException:
        try:
            store.abort(oid)
        except Exception:
            pass
        raise
    return _safe_get(store, oid)


def _current_worker():
    try:
        from ray_tpu._private.worker_runtime import current_worker

        return current_worker()
    except Exception:
        return None


def _safe_get(store, oid: bytes):
    try:
        return store.get(oid)
    except Exception:
        return None
