"""HTTP proxy actor: HTTP front door → router → replica.

Reference: python/ray/serve/_private/http_proxy.py:189 (HTTPProxy, one
actor per node, uvicorn/starlette) and http_state.py. Ours serves with the
stdlib ThreadingHTTPServer — thread-per-request maps onto the runtime's
thread-based actors, keeps zero extra dependencies, and the proxy is not on
the TPU hot path (model compute happens in the replica's jax program).

Request → longest-prefix route match → per-deployment Router (long-poll
updated) → replica ``handle_request``. The user callable receives a
``serve.Request``; returns str/bytes/dict (dict ⇒ JSON), or a
``serve.Response`` for full control.
"""
from __future__ import annotations

import json
import threading
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, urlparse

from ray_tpu.serve._private.constants import ROUTE_TABLE_KEY
from ray_tpu.serve._private.long_poll import LongPollClient


class Request:
    """What an HTTP-ingress user callable receives (starlette.Request
    analog, minimal)."""

    def __init__(self, method: str, path: str, query_params: dict,
                 headers: dict, body: bytes):
        self.method = method
        self.path = path
        self.query_params = query_params
        self.headers = headers
        self.body = body

    def json(self):
        return json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()

    def __reduce__(self):
        return (Request, (self.method, self.path, self.query_params,
                          self.headers, self.body))


class Response:
    def __init__(self, body, status_code: int = 200,
                 content_type: str | None = None, headers: dict | None = None):
        self.body = body
        self.status_code = status_code
        self.content_type = content_type
        self.headers = headers or {}

    def __reduce__(self):
        return (Response, (self.body, self.status_code, self.content_type,
                           self.headers))


class StreamingResponse:
    """A chunked/streaming HTTP response: ``body`` is an iterator (or
    iterable) of str/bytes chunks. Reference:
    serve/_private/http_proxy.py streams starlette StreamingResponses;
    here the replica keeps the generator and the proxy pulls chunk
    batches over actor calls, relaying them with HTTP chunked transfer
    encoding — each batch reaches the client as soon as it is produced
    (token streaming for TPU model serving is the motivating case).

    A bare generator returned from a deployment streams too, with
    default status/headers.
    """

    def __init__(self, body, status_code: int = 200,
                 content_type: str = "text/plain",
                 headers: dict | None = None):
        self.body = body
        self.status_code = status_code
        self.content_type = content_type
        self.headers = headers or {}


def _encode_response(result) -> tuple[int, bytes, str, dict]:
    if isinstance(result, Response):
        body = result.body
        if isinstance(body, (dict, list)):
            raw = json.dumps(body).encode()
            ctype = result.content_type or "application/json"
        elif isinstance(body, bytes):
            raw, ctype = body, result.content_type or "application/octet-stream"
        else:
            raw = str(body).encode()
            ctype = result.content_type or "text/plain"
        return result.status_code, raw, ctype, result.headers
    if isinstance(result, (dict, list)):
        return 200, json.dumps(result).encode(), "application/json", {}
    if isinstance(result, bytes):
        return 200, result, "application/octet-stream", {}
    return 200, str(result).encode(), "text/plain", {}


class HTTPProxyActor:
    """The actor body. Holds the HTTP server + routing state."""

    def __init__(self, host: str, port: int, controller_name: str,
                 controller_namespace: str = "serve"):
        import ray_tpu

        self._controller = ray_tpu.get_actor(
            controller_name, namespace=controller_namespace)
        self._routes: dict[str, dict] = {}
        self._routes_lock = threading.Lock()
        self._long_poll = LongPollClient(
            self._controller, {ROUTE_TABLE_KEY: self._update_routes})

        proxy = self

        class _Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):      # silence per-request stderr spam
                pass

            def _do(self):
                proxy._handle_http(self)

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _do

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self._port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="serve-http")
        self._thread.start()

    # ------------------------------------------------------------ callbacks
    def _update_routes(self, routes):
        with self._routes_lock:
            self._routes = dict(routes or {})

    # -------------------------------------------------------------- serving
    def _match_route(self, path: str):
        with self._routes_lock:
            best = None
            for prefix, info in self._routes.items():
                norm = prefix.rstrip("/") or "/"
                if path == norm or path.startswith(
                        norm if norm == "/" else norm + "/"):
                    if best is None or len(norm) > len(best[0]):
                        best = (norm, info)
            return best

    def _handle_http(self, h: BaseHTTPRequestHandler):
        try:
            parsed = urlparse(h.path)
            path = parsed.path
            if path == "/-/healthz":
                self._send(h, 200, b"success", "text/plain", {})
                return
            if path == "/-/routes":
                with self._routes_lock:
                    body = json.dumps({p: i["app_name"]
                                       for p, i in self._routes.items()})
                self._send(h, 200, body.encode(), "application/json", {})
                return
            match = self._match_route(path)
            if match is None:
                self._send(h, 404, b'{"error": "no route"}',
                           "application/json", {})
                return
            _prefix, info = match
            length = int(h.headers.get("Content-Length") or 0)
            body = h.rfile.read(length) if length else b""
            request = Request(
                h.command, path, dict(parse_qsl(parsed.query)),
                {k.lower(): v for k, v in h.headers.items()}, body)
            from ray_tpu.serve.handle import DeploymentResponse, _get_router

            router = _get_router(info["ingress_deployment"])
            response = DeploymentResponse(router, "__call__", (request,), {})
            result = response.result(timeout_s=60.0)
            from ray_tpu.serve.handle import _StreamChunkIterator

            if isinstance(result, _StreamChunkIterator):
                self._send_stream(h, result)
                return
            status, raw, ctype, headers = _encode_response(result)
            self._send(h, status, raw, ctype, headers)
        except Exception as e:
            from ray_tpu.exceptions import ServeOverloadedError

            if isinstance(e, ServeOverloadedError):
                # admission control shed the request: 503 + Retry-After,
                # the standard backpressure contract for HTTP callers
                try:
                    self._send(
                        h, 503,
                        json.dumps({"error": str(e),
                                    "retry_after_s": e.retry_after_s}
                                   ).encode(),
                        "application/json",
                        {"Retry-After":
                         str(max(1, int(round(e.retry_after_s))))})
                except Exception:
                    pass
                return
            tb = traceback.format_exc()
            try:
                self._send(h, 500,
                           json.dumps({"error": str(e),
                                       "traceback": tb}).encode(),
                           "application/json", {})
            except Exception:
                pass

    @staticmethod
    def _send_stream(h, it):
        """Relay a replica-held generator (surfaced by the handle layer
        as a _StreamChunkIterator) with HTTP chunked transfer encoding:
        each chunk flushes to the socket the moment the replica yields
        it. Mid-stream failures can only truncate the chunked body
        (status already went out) — the client's decoder reports the
        missing terminator instead of a silent short read."""
        h.send_response(it.status_code)
        h.send_header("Content-Type", it.content_type or "text/plain")
        h.send_header("Transfer-Encoding", "chunked")
        for k, v in (getattr(it, "headers", None) or {}).items():
            h.send_header(k, v)
        h.end_headers()
        try:
            for c in it:
                if c:
                    h.wfile.write(f"{len(c):x}\r\n".encode() + c + b"\r\n")
                    h.wfile.flush()
            h.wfile.write(b"0\r\n\r\n")
            h.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            h.close_connection = True
            it.cancel()   # client went away: drop the replica's generator
        except Exception:
            # generator raised mid-stream: a 500 is impossible now —
            # terminate the chunked body abnormally (no 0-chunk) AND close
            # the keep-alive socket, or the client would block waiting for
            # the next chunk on an open connection
            h.close_connection = True
            traceback.print_exc()
            it.cancel()

    @staticmethod
    def _send(h, status, raw: bytes, ctype: str, headers: dict):
        h.send_response(status)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(raw)))
        for k, v in headers.items():
            h.send_header(k, v)
        h.end_headers()
        h.wfile.write(raw)

    # ----------------------------------------------------------------- RPC
    def ready(self) -> int:
        """Returns the bound port (0-port binds resolve here)."""
        return self._port

    def shutdown(self):
        self._long_poll.stop()
        self._server.shutdown()
        return True
