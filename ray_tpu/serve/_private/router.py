"""Router: picks a replica for each request, capping in-flight per replica.

Reference: python/ray/serve/_private/router.py — Router at :261,
ReplicaSet._try_assign_replica (in-flight-capped selection) at :134. Ours
uses power-of-two-choices over the in-flight counts (the reference's newer
replica scheduler does the same); when every replica is at its cap the
request queues on a condition variable until a slot frees.

Completion tracking: one monitor thread per Router waits on outstanding
ObjectRefs (batched ``wait``) and releases slots as tasks finish — the
equivalent of the reference's asyncio done-callbacks.
"""
from __future__ import annotations

import random
import threading
import uuid

from ray_tpu.serve._private.constants import replicas_key
from ray_tpu.serve._private.long_poll import LongPollClient


class _ReplicaSlot:
    __slots__ = ("replica_id", "handle", "in_flight")

    def __init__(self, replica_id, handle):
        self.replica_id = replica_id
        self.handle = handle
        self.in_flight = 0


class Router:
    def __init__(self, controller_handle, deployment_id: str,
                 max_ongoing_requests: int = 8):
        self._controller = controller_handle
        self._deployment_id = deployment_id
        self._max_ongoing = max_ongoing_requests
        self._lock = threading.Condition()
        self._replicas: dict[str, _ReplicaSlot] = {}
        self._outstanding: dict = {}   # ObjectRef -> replica_id
        self._num_queued = 0           # callers blocked waiting for a slot
        # stable identity for controller-side demand bookkeeping: id(self)
        # collides across processes (proxy vs driver handles)
        self._router_id = uuid.uuid4().hex
        self._last_metrics_push = 0.0
        self._stopped = threading.Event()
        self._long_poll = LongPollClient(
            controller_handle,
            {replicas_key(deployment_id): self._update_replicas})
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name=f"serve-router-{deployment_id}")
        self._monitor.start()

    # ------------------------------------------------------------ callbacks
    def _update_replicas(self, info):
        """Long-poll callback: (replica list, max_ongoing) snapshot."""
        import ray_tpu

        if info is None:
            entries, cap = [], self._max_ongoing
        else:
            entries, cap = info["replicas"], info["max_ongoing_requests"]
        with self._lock:
            self._max_ongoing = cap
            seen = set()
            for entry in entries:
                rid, name = entry["replica_id"], entry["actor_name"]
                seen.add(rid)
                if rid not in self._replicas:
                    try:
                        handle = ray_tpu.get_actor(
                            name, namespace="serve")
                    except ValueError:
                        continue   # died between snapshot and now
                    self._replicas[rid] = _ReplicaSlot(rid, handle)
            for rid in list(self._replicas):
                if rid not in seen:
                    del self._replicas[rid]
            self._lock.notify_all()

    # ------------------------------------------------------------- requests
    def assign_request(self, method_name: str, args, kwargs,
                       timeout_s: float = 30.0):
        """Pick a replica (p2c by in-flight, capped) and submit. Returns
        (ObjectRef, replica_id) of the replica call."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._lock:
            self._num_queued += 1
            try:
                while True:
                    slot = self._pick_slot()
                    if slot is not None:
                        slot.in_flight += 1
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise TimeoutError(
                            f"no replica of {self._deployment_id} available "
                            f"within {timeout_s}s "
                            f"({len(self._replicas)} replicas, all at "
                            f"max_ongoing_requests={self._max_ongoing})")
                    self._lock.wait(min(remaining, 0.5))
            finally:
                self._num_queued -= 1
        try:
            ref = slot.handle.handle_request.remote(
                method_name, args, kwargs)
        except Exception:
            with self._lock:
                slot.in_flight -= 1
                self._lock.notify_all()
            raise
        with self._lock:
            self._outstanding[ref] = slot.replica_id
            self._lock.notify_all()   # wake monitor
        return ref, slot.replica_id

    def mark_replica_dead(self, replica_id: str):
        """Drop a replica observed dead by a caller (ActorDiedError on its
        result). The long-poll will also remove it once the controller
        notices — this is the fast path so retries don't re-pick it."""
        with self._lock:
            self._replicas.pop(replica_id, None)
            for ref, rid in list(self._outstanding.items()):
                if rid == replica_id:
                    del self._outstanding[ref]
            self._lock.notify_all()

    def _pick_slot(self):
        live = [s for s in self._replicas.values()
                if s.in_flight < self._max_ongoing]
        if not live:
            return None
        if len(live) == 1:
            return live[0]
        a, b = random.sample(live, 2)
        return a if a.in_flight <= b.in_flight else b

    # -------------------------------------------------------------- monitor
    def _monitor_loop(self):
        import time

        import ray_tpu

        while not self._stopped.is_set():
            # push handle-side metrics (queued + in-flight) so the
            # controller's autoscaler sees demand the replicas can't
            # (reference: handle-side autoscaling metrics push)
            now = time.monotonic()
            if now - self._last_metrics_push >= 0.2:
                self._last_metrics_push = now
                with self._lock:
                    queued = self._num_queued
                    in_flight = sum(s.in_flight
                                    for s in self._replicas.values())
                try:
                    self._controller.record_handle_metrics.remote(
                        self._deployment_id, self._router_id,
                        queued + in_flight)
                except Exception:
                    pass
            with self._lock:
                refs = list(self._outstanding)
            if not refs:
                with self._lock:
                    self._lock.wait(0.2)
                continue
            try:
                done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.5,
                                       fetch_local=False)
            except Exception:
                done = []
            if done:
                with self._lock:
                    for ref in done:
                        rid = self._outstanding.pop(ref, None)
                        slot = self._replicas.get(rid)
                        if slot is not None:
                            slot.in_flight = max(0, slot.in_flight - 1)
                    self._lock.notify_all()

    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)

    def stop(self):
        self._stopped.set()
        self._long_poll.stop()
