"""Router: picks a replica for each request, capping in-flight per replica.

Reference: python/ray/serve/_private/router.py — Router at :261,
ReplicaSet._try_assign_replica (in-flight-capped selection) at :134. Ours
uses power-of-two-choices over the live per-replica queue depth (in-flight
count — the reference's newer replica scheduler does the same); when every
replica is at its cap the request queues on a condition variable until a
slot frees.

**Admission control:** the wait queue is BOUNDED at
``max_queued_requests`` per replica. A request arriving with every replica
saturated and the queue full is shed immediately with a typed
``ServeOverloadedError`` carrying a retry-after hint (plus a
``REQUEST_SHED`` cluster event and ``ray_tpu_serve_shed_total``) — the
production contract is fast feedback for the marginal caller, not
unbounded latency for every caller.

**Millisecond failover:** besides the long-poll replica-set updates (the
slow path: controller notices → broadcasts), the router subscribes
directly to the GCS actor-death feed (``watch_actor_deaths``, the PR 5
machinery that poisons collective groups in ~tens of ms). A dead
replica's slot is dropped the moment the GCS publishes the death: new
requests never pick it, queued callers re-pick a survivor, and in-flight
requests on it are flagged so their ``DeploymentResponse.result()``
re-dispatches without waiting for the object layer to surface
``ActorDiedError``.

Completion tracking: one monitor thread per Router waits on outstanding
ObjectRefs (batched ``wait``) and releases slots as tasks finish — the
equivalent of the reference's asyncio done-callbacks.
"""
from __future__ import annotations

import random
import threading
import uuid

from ray_tpu._private import events as _events
from ray_tpu._private import telemetry as _tm
from ray_tpu.exceptions import ServeOverloadedError
from ray_tpu.serve._private.constants import replicas_key
from ray_tpu.serve._private.long_poll import LongPollClient


class _ReplicaSlot:
    __slots__ = ("replica_id", "handle", "in_flight")

    def __init__(self, replica_id, handle):
        self.replica_id = replica_id
        self.handle = handle
        self.in_flight = 0


class Router:
    def __init__(self, controller_handle, deployment_id: str,
                 max_ongoing_requests: int = 8,
                 max_queued_requests: int = 32):
        self._controller = controller_handle
        self._deployment_id = deployment_id
        self._max_ongoing = max_ongoing_requests
        self._max_queued = max_queued_requests
        self._lock = threading.Condition()
        self._replicas: dict[str, _ReplicaSlot] = {}
        self._actor_to_replica: dict[str, str] = {}   # actor_id hex → rid
        self._outstanding: dict = {}   # ObjectRef -> replica_id
        self._num_queued = 0           # callers blocked waiting for a slot
        # replicas observed dead (death feed / caller-observed) whose
        # in-flight requests must fail over; an insertion-ordered dict
        # used as a set so the overflow trim drops the OLDEST ids (ids
        # never recur, so old entries are safe to forget)
        self._dead: dict[str, None] = {}
        # replicas the controller broadcast as DRAINING (scale-down or
        # preemption-warned): rid → wall-clock drain deadline. Drives
        # both proactive de-selection and the shed retry-after hint
        # (back off past the grace window, not the static default)
        self._draining: dict[str, float] = {}
        # stable identity for controller-side demand bookkeeping: id(self)
        # collides across processes (proxy vs driver handles)
        self._router_id = uuid.uuid4().hex
        self._last_metrics_push = 0.0
        self._stopped = threading.Event()
        self._death_watch = None
        self._death_watch_tried = False
        self._long_poll = LongPollClient(
            controller_handle,
            {replicas_key(deployment_id): self._update_replicas})
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name=f"serve-router-{deployment_id}")
        self._monitor.start()

    # ------------------------------------------------------------ callbacks
    def _update_replicas(self, info):
        """Long-poll callback: (replica list, caps) snapshot.

        Handle resolution (``get_actor`` — a GCS round trip per NEW
        replica) happens OUTSIDE the router lock: with it held, one
        slow/reconnecting GCS call froze every ``assign_request`` and
        the monitor loop for its duration (raylint RTL101). Resolved
        handles are installed under the lock with a re-check, so a
        replica that died (or was superseded) mid-resolution is never
        installed over fresher state."""
        import ray_tpu

        if info is None:
            entries, cap, queued_cap = [], self._max_ongoing, self._max_queued
            draining = []
        else:
            entries = info["replicas"]
            cap = info["max_ongoing_requests"]
            queued_cap = info.get("max_queued_requests", self._max_queued)
            draining = info.get("draining") or []
        with self._lock:
            missing = [(e["replica_id"], e["actor_name"]) for e in entries
                       if e["replica_id"] not in self._replicas
                       and e["replica_id"] not in self._dead]
        resolved = []
        for rid, name in missing:
            try:
                resolved.append((rid, ray_tpu.get_actor(
                    name, namespace="serve")))
            except ValueError:
                continue   # died between snapshot and now
        with self._lock:
            self._max_ongoing = cap
            self._max_queued = queued_cap
            seen = set()
            actor_map = {}
            for entry in entries:
                seen.add(entry["replica_id"])
                if entry.get("actor_id"):
                    actor_map[entry["actor_id"]] = entry["replica_id"]
            for rid, handle in resolved:
                if rid in seen and rid not in self._replicas \
                        and rid not in self._dead:
                    self._replicas[rid] = _ReplicaSlot(rid, handle)
            for rid in list(self._replicas):
                if rid not in seen:
                    del self._replicas[rid]
            self._actor_to_replica = actor_map
            import time as _time

            now = _time.time()
            self._draining = {
                d["replica_id"]: float(d["deadline_ts"])
                for d in draining if float(d["deadline_ts"]) > now}
            for rid in self._draining:
                self._replicas.pop(rid, None)
            self._lock.notify_all()
        self._ensure_death_watch()

    # ----------------------------------------------------------- death feed
    def _ensure_death_watch(self):
        """Subscribe (once) to the GCS actor-death feed so a dead replica
        sheds traffic in milliseconds instead of a health-check period.
        Best-effort: with no worker runtime attached (bare unit tests)
        the router degrades to long-poll-only updates."""
        if self._death_watch_tried:
            return
        self._death_watch_tried = True
        try:
            from ray_tpu._private.pubsub import watch_actor_deaths

            self._death_watch = watch_actor_deaths(self._on_actor_death)
        except Exception:
            self._death_watch = None

    def _on_actor_death(self, actor_id, reason: str):
        hex_id = actor_id.hex() if isinstance(actor_id, bytes) else actor_id
        with self._lock:
            rid = self._actor_to_replica.get(hex_id)
            if rid is None:
                return
        self.mark_replica_dead(rid)

    def has_death_watch(self) -> bool:
        return self._death_watch is not None

    def replica_dead(self, replica_id: str) -> bool:
        with self._lock:
            return replica_id in self._dead

    # ------------------------------------------------------------- requests
    def assign_request(self, method_name: str, args, kwargs,
                       timeout_s: float = 30.0):
        """Pick a replica (p2c by queue depth, capped) and submit. Returns
        (ObjectRef, replica_id) of the replica call. Sheds with
        ``ServeOverloadedError`` when saturated AND the bounded queue is
        full — admission control, not unbounded queueing."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._lock:
            slot = self._pick_slot()
            if slot is None:
                cap = self._queue_capacity()
                # Shed only when we KNOW the capacity is saturated: with
                # an empty replica view (cold start before the first
                # long-poll snapshot, or every replica momentarily dead
                # awaiting replacement) there is no capacity denominator
                # to judge overload against — queue until the deadline
                # instead of shedding traffic the deployment could serve
                # a few ms later.
                if self._replicas and self._num_queued >= cap:
                    self._shed_locked(cap)
                self._num_queued += 1
                try:
                    while True:
                        slot = self._pick_slot()
                        if slot is not None:
                            break
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            raise TimeoutError(
                                f"no replica of {self._deployment_id} "
                                f"available within {timeout_s}s "
                                f"({len(self._replicas)} replicas, all at "
                                f"max_ongoing_requests={self._max_ongoing})")
                        self._lock.wait(min(remaining, 0.5))
                finally:
                    self._num_queued -= 1
            slot.in_flight += 1
        try:
            ref = slot.handle.handle_request.remote(
                method_name, args, kwargs)
        except Exception:
            with self._lock:
                slot.in_flight -= 1
                self._lock.notify_all()
            raise
        with self._lock:
            self._outstanding[ref] = slot.replica_id
            self._lock.notify_all()   # wake monitor
        return ref, slot.replica_id

    def _queue_capacity(self) -> int:
        """Bounded-queue size: ``max_queued_requests`` PER replica.
        (Shedding is additionally gated on a non-empty replica view —
        see assign_request — so cold-start traffic queues instead of
        being shed against a capacity of zero.)"""
        return self._max_queued * max(1, len(self._replicas))

    def _shed_locked(self, cap: int):
        """Reject one request at admission (caller holds the lock)."""
        import time as _time

        queued = self._num_queued
        # drain-aware backoff: when replicas are preemption-warned (or
        # scale-down-draining), the shed is a capacity STORM, not a load
        # blip — hint the grace window remaining so clients back off
        # past it instead of hammering a draining app
        now = _time.time()
        self._draining = {rid: dl for rid, dl in self._draining.items()
                          if dl > now}
        drain_deadline = max(self._draining.values(), default=None)
        if drain_deadline is not None:
            retry_after = max(0.1, min(30.0, drain_deadline - now + 0.25))
            draining = True
        else:
            # half a max_ongoing drain at ~10 rps per replica is a crude
            # but bounded hint; clients with real latency knowledge
            # should use their own backoff
            retry_after = max(0.1, min(5.0, 0.05 * (1 + queued)))
            draining = False
        _tm.counter_inc("ray_tpu_serve_shed_total",
                        tags={"deployment": self._deployment_id})
        _events.record("REQUEST_SHED", deployment=self._deployment_id,
                       queued=queued, queue_capacity=cap,
                       retry_after_s=retry_after, draining=draining)
        raise ServeOverloadedError(self._deployment_id, queued, retry_after,
                                   draining)

    def mark_replica_dead(self, replica_id: str):
        """Drop a replica observed dead (GCS death feed, or a caller's
        ActorDiedError on its result). The long-poll will also remove it
        once the controller notices — this is the fast path so queued
        callers and retries never re-pick it, and in-flight requests on
        it fail over immediately (``replica_dead`` flag polled by
        DeploymentResponse)."""
        with self._lock:
            if replica_id in self._dead:
                return
            self._dead[replica_id] = None
            if len(self._dead) > 512:   # bounded: evict the oldest half
                for rid in list(self._dead)[:256]:
                    del self._dead[rid]
            self._replicas.pop(replica_id, None)
            failing_over = 0
            for ref, rid in list(self._outstanding.items()):
                if rid == replica_id:
                    del self._outstanding[ref]
                    failing_over += 1
            self._lock.notify_all()
        if failing_over:
            _tm.counter_inc("ray_tpu_serve_failovers_total", failing_over,
                            tags={"deployment": self._deployment_id})

    def mark_replica_draining(self, replica_id: str):
        """Drop a replica that refused a request with
        ``ReplicaDrainingError`` from the selection set WITHOUT flagging
        it dead: its other in-flight requests were accepted before the
        drain and will complete (flagging dead would re-dispatch them —
        double execution). Needed because a draining replica rejects
        instantly, so its in_flight stays ~0 and power-of-two-choices
        would otherwise RE-PICK it for every retry until the
        controller's post-drain broadcast lands, burning the whole
        retry budget on one drainer while healthy survivors sit busy.
        (A stale pre-drain broadcast may briefly re-add it; the next
        rejection removes it again — bounded, and the post-drain
        broadcast ends the cycle.)"""
        with self._lock:
            self._replicas.pop(replica_id, None)
            self._lock.notify_all()

    def _pick_slot(self):
        live = [s for s in self._replicas.values()
                if s.in_flight < self._max_ongoing]
        if not live:
            return None
        if len(live) == 1:
            return live[0]
        a, b = random.sample(live, 2)
        return a if a.in_flight <= b.in_flight else b

    # -------------------------------------------------------------- monitor
    def _monitor_loop(self):
        import time

        import ray_tpu

        while not self._stopped.is_set():
            # push handle-side metrics (queued + in-flight) so the
            # controller's autoscaler sees demand the replicas can't
            # (reference: handle-side autoscaling metrics push)
            now = time.monotonic()
            if now - self._last_metrics_push >= 0.2:
                self._last_metrics_push = now
                with self._lock:
                    queued = self._num_queued
                    in_flight = sum(s.in_flight
                                    for s in self._replicas.values())
                _tm.gauge_set("ray_tpu_serve_queue_depth_tasks",
                              queued + in_flight,
                              tags={"deployment": self._deployment_id,
                                    "role": _tm.role()})
                try:
                    self._controller.record_handle_metrics.remote(
                        self._deployment_id, self._router_id,
                        queued + in_flight)
                except Exception:
                    pass
            with self._lock:
                refs = list(self._outstanding)
            if not refs:
                with self._lock:
                    self._lock.wait(0.2)
                continue
            try:
                done, _ = ray_tpu.wait(refs, num_returns=1, timeout=0.5,
                                       fetch_local=False)
            except Exception:
                done = []
            if done:
                with self._lock:
                    for ref in done:
                        rid = self._outstanding.pop(ref, None)
                        slot = self._replicas.get(rid)
                        if slot is not None:
                            slot.in_flight = max(0, slot.in_flight - 1)
                    self._lock.notify_all()

    def num_replicas(self) -> int:
        with self._lock:
            return len(self._replicas)

    def stop(self):
        self._stopped.set()
        self._long_poll.stop()
        watch, self._death_watch = self._death_watch, None
        if watch is not None:
            try:
                watch.stop()
            except Exception:
                pass
