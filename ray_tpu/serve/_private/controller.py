"""ServeController actor: owns all deployment state and reconciles it.

Reference: python/ray/serve/controller.py:61 (ServeController),
serve/_private/deployment_state.py:958 (DeploymentState FSM; scale loop at
:1281,1623; ActorReplicaWrapper at :168) and
serve/_private/autoscaling_policy.py. One detached named actor; a
background thread runs the reconcile loop:

    target state (app specs) ──reconcile──▶ replica actors
                                     │
                 long-poll push ◀────┘  (routers/proxies learn replica sets)

Replica FSM: STARTING ─ready──▶ RUNNING ─drain──▶ STOPPING ─▶ gone; a
failed health check or dead actor re-enters through STARTING via a fresh
replica (replicas are cattle — same as the reference).

Fast failure detection: besides per-replica health checks (period
``health_check_period_s``), the controller subscribes to the GCS
actor-death feed (PR 5's ``watch_actor_deaths``). A dead replica is
dropped and re-broadcast within the feed's publish latency — routers
stop routing to it in milliseconds, and the scale loop starts the
replacement on the next tick instead of a health-check period later.

Observability: replica lifecycle lands in the cluster event log
(``REPLICA_STARTED`` / ``REPLICA_DIED`` / ``REPLICA_DRAINED``), autoscale
decisions as ``SERVE_SCALED``; the metric catalog carries the FSM
occupancy gauge (``ray_tpu_serve_replicas_tasks``), replacement counters
(``ray_tpu_serve_replica_restarts_total{reason}``) and autoscale
decisions (``ray_tpu_serve_autoscale_total{direction}``).

**Serve as a tenant (multi-tenant control plane).** An app deployed with
``serve.run(..., job=...)`` is a first-class tenant of the PR 13
job/quota/preemption plane: the controller registers the job
(quota + priority) and every replica is backed by a one-bundle capacity
placement group named by the replica's slot tag
(``serve-<app>-<dep>-slot<k>``), labeled with the app's job. The gang IS
the replica's capacity claim — a STARTING replica only turns RUNNING
once its gang is CREATED, so a demand spike on a high-priority app
contends in the job plane (and preempts a lower-priority training gang)
instead of silently oversubscribing. The flip side:

- a ``preempt_warning`` on a replica's gang (higher-priority tenant, or
  seeded chaos via ``preempt_job:<job>``) marks the replica WARNED:
  it is treated as already-lost capacity (the autoscaler/scale loop
  starts the replacement before the grace window expires), it begins
  draining immediately, and routers learn via the ``draining`` list in
  the long-poll broadcast (``SERVE_REPLICA_WARNED`` event,
  ``ray_tpu_serve_warned_replicas_tasks`` gauge);
- scale-down itself rides the SAME warning machinery: the controller
  self-preempts the victim slot's gang (``preempt_job`` narrowed by
  ``pg_name``), drains through the grace window, and removes the gang
  pre-fire — the controlled-drain escape hatch — so capacity returns to
  queued training gangs the moment the drain completes, with zero lost
  accepted requests (kill switch: ``serve_preempt_scale_down=0``).
"""
from __future__ import annotations

import os
import threading
import time
import uuid

from ray_tpu._private import events as _events
from ray_tpu._private import telemetry as _tm
from ray_tpu.serve._private.constants import (
    ROUTE_TABLE_KEY,
    deployment_id as make_dep_id,
    replicas_key,
    slot_tag,
)
from ray_tpu.serve._private.long_poll import LongPollHost
from ray_tpu.serve.config import DeploymentConfig

STARTING, RUNNING, STOPPING = "STARTING", "RUNNING", "STOPPING"
RECONCILE_PERIOD_S = 0.1


def _worker_gcs_call(method: str, **kw):
    """Default GCS transport: this process's worker connection. The sim
    cluster injects its own (no worker runtime there)."""
    from ray_tpu._private import api

    return api._require_worker().gcs.call(method, **kw)


class _Replica:
    def __init__(self, replica_id, actor_name, handle, ready_ref,
                 slot: int = 0):
        self.replica_id = replica_id
        self.actor_name = actor_name
        self.handle = handle
        self.slot = slot
        self.actor_id_hex = getattr(handle, "_actor_id", b"").hex()
        self.state = STARTING
        self.ready_ref = ready_ref
        self.drain_ref = None
        self.drain_deadline = None
        self.health_ref = None
        self.health_deadline = None
        self.last_health_check = time.monotonic()
        self.metrics_ref = None
        self.num_ongoing = 0.0
        # job-plane capacity (tenant apps only): the slot-named gang
        # backing this replica, and its observed preemption state
        self.capacity_pg_id: bytes | None = None
        self.pg_created = False
        self.pg_requested_ts = 0.0
        self.warned = False                 # preempt_warning observed
        self.warn_deadline: float | None = None   # wall clock (GCS stamp)
        self.drain_requested = False        # controller self-preempted


class _DeploymentState:
    """Target + actual state for one deployment."""

    def __init__(self, dep_id: str, spec: dict, host: LongPollHost,
                 job: str = "", gcs_call=None):
        self.dep_id = dep_id
        self.spec = spec                       # user_callable/init args/...
        self.config = DeploymentConfig.from_dict(spec["config"])
        self.host = host
        self.job = job                         # "" = not a job-plane tenant
        self._gcs_call = gcs_call or _worker_gcs_call
        self.replicas: list[_Replica] = []
        self.deleting = False
        self.version = spec.get("version") or "1"
        # autoscaling bookkeeping
        ac = self.config.autoscaling_config
        self.target_num = (ac.min_replicas if ac
                           else self.config.num_replicas)
        self._scale_proposal_since: tuple[int, float] | None = None
        self._last_metrics_poll = 0.0
        self._last_capacity_poll = 0.0
        # handle-side demand: {router_id: (queued+in_flight, monotonic ts)}
        self.handle_metrics: dict[str, tuple[float, float]] = {}

    # ---------------------------------------------------------- target edit
    def update_spec(self, spec: dict):
        old_config = self.config
        self.spec = spec
        self.config = DeploymentConfig.from_dict(spec["config"])
        new_version = spec.get("version") or "1"
        code_changed = new_version != self.version
        self.version = new_version
        ac = self.config.autoscaling_config
        if ac:
            self.target_num = max(ac.min_replicas,
                                  min(ac.max_replicas, self.target_num))
        else:
            self.target_num = self.config.num_replicas
        if code_changed:
            # roll every replica (simple stop-all; the reference does a
            # gradual rolling update — acceptable simplification, the FSM
            # recreates capacity on the next ticks)
            for r in self.replicas:
                if r.state != STOPPING:
                    self._begin_stop(r)
        elif old_config.user_config != self.config.user_config:
            for r in self.replicas:
                if r.state == RUNNING:
                    try:
                        r.handle.reconfigure.remote(self.config.user_config)
                    except Exception:
                        pass

    def mark_deleting(self):
        self.deleting = True
        for r in self.replicas:
            if r.state != STOPPING:
                self._begin_stop(r)

    # ------------------------------------------------------------ reconcile
    def reconcile(self) -> bool:
        """One tick. Returns True when (deleting and fully stopped)."""
        # 0. job-plane capacity tracking (tenant apps): placed gangs
        #    unblock STARTING replicas; preempt warnings start drains
        changed = self._poll_capacity()
        # 1. STARTING → RUNNING when ready_ref resolves. A tenant
        #    replica additionally needs its capacity gang CREATED —
        #    placed capacity IS part of readiness; until then the
        #    replica waits in the job plane's queue like any gang.
        for r in self.replicas:
            if r.state == STARTING:
                if r.capacity_pg_id is not None and not r.pg_created:
                    continue
                ready = self._check_ready(r)
                if ready == "ready":
                    r.state = RUNNING
                    _events.record("REPLICA_STARTED",
                                   deployment=self.dep_id,
                                   replica_id=r.replica_id)
                    changed = True
                elif ready == "failed":
                    self._drop(r, reason="init")
                    changed = True
        # 2. reap STOPPING
        for r in list(self.replicas):
            if r.state == STOPPING:
                drained = self._check_drained(r)
                if drained or time.monotonic() > r.drain_deadline:
                    _events.record("REPLICA_DRAINED",
                                   deployment=self.dep_id,
                                   replica_id=r.replica_id,
                                   graceful=drained)
                    self._kill(r)
                    changed = True
        if self.deleting:
            if not self.replicas:
                self._set_replica_gauges()
                return True
            return False
        # 3. health checks on RUNNING
        changed |= self._health_checks()
        # 4. autoscaling metrics + decision
        self._autoscale()
        # 5. scale toward target. A preemption-warned (or self-draining)
        #    replica is already-lost capacity: excluding it here starts
        #    the replacement BEFORE the grace window expires, not after
        #    the death event.
        live = self._live()
        if len(live) < self.target_num:
            for _ in range(self.target_num - len(live)):
                self._start_replica()
            changed = True
        elif len(live) > self.target_num:
            # stop youngest first (prefer keeping warmed replicas)
            extra = len(live) - self.target_num
            for r in reversed(live):
                if extra == 0:
                    break
                if r.state == STARTING or r.state == RUNNING:
                    self._scale_down_replica(r)
                    extra -= 1
            changed = True
        if changed:
            self.broadcast()
            self._set_replica_gauges()
        return False

    def _live(self) -> list:
        """Replicas that count as (current or incoming) capacity."""
        return [r for r in self.replicas
                if r.state in (STARTING, RUNNING)
                and not r.warned and not r.drain_requested]

    def _check_ready(self, r: _Replica) -> str:
        """'ready' | 'pending' | 'failed' for a STARTING replica (the sim
        plane overrides this — no actors there)."""
        import ray_tpu

        try:
            done, _ = ray_tpu.wait([r.ready_ref], timeout=0)
        except Exception:
            done = []
        if not done:
            return "pending"
        try:
            # surface init errors; the ref is already done (wait above),
            # so the timeout only bounds the result fetch — timeout-less,
            # a wedged store fetch would stall the whole control loop
            # under the controller lock (raylint RTL102)
            ray_tpu.get(r.ready_ref, timeout=10.0)
            return "ready"
        except Exception:
            return "failed"

    def _check_drained(self, r: _Replica) -> bool:
        import ray_tpu

        if r.drain_ref is None:
            return False
        try:
            done, _ = ray_tpu.wait([r.drain_ref], timeout=0)
            return bool(done)
        except Exception:
            return True

    # ------------------------------------------------- job-plane capacity
    def _poll_capacity(self) -> bool:
        """Track each replica's capacity gang in the job plane. Polling
        (0.25s cadence) rather than a pubsub subscription: the snapshot
        carries everything needed (State + PreemptDeadline), and a missed
        push can never wedge the FSM."""
        if not self.job:
            return False
        now = time.monotonic()
        if now - self._last_capacity_poll < 0.25:
            return False
        self._last_capacity_poll = now
        changed = False
        for r in list(self.replicas):
            if r.capacity_pg_id is None:
                continue
            try:
                snap = self._gcs_call("get_placement_group",
                                      pg_id=r.capacity_pg_id)
            except Exception:
                continue
            if snap is None:
                # gang removed out from under us (operator / chaos):
                # the capacity claim is gone — replace the replica
                if r.state != STOPPING:
                    r.capacity_pg_id = None
                    self._drop(r, reason="preempted")
                    changed = True
                continue
            state = snap.get("State")
            if not r.pg_created and state == "CREATED":
                r.pg_created = True
                wait_s = now - r.pg_requested_ts
                _tm.observe("ray_tpu_serve_capacity_wait_seconds", wait_s,
                            tags={"deployment": self.dep_id})
                _events.record("SERVE_CAPACITY_PLACED",
                               deployment=self.dep_id,
                               replica_id=r.replica_id, job=self.job,
                               wait_s=round(wait_s, 4))
                changed = True
                continue
            if r.pg_created and state != "CREATED":
                # the grace window expired and the preemption FIRED (the
                # gang re-queued PENDING): capacity is gone NOW — kill
                # the replica and remove the zombie gang so it doesn't
                # contend for capacity the app no longer holds
                if r.state != STOPPING:
                    self._drop(r, reason="preempted")
                else:
                    self._kill(r)
                changed = True
                continue
            deadline = snap.get("PreemptDeadline")
            if deadline and not r.warned and r.state != STOPPING:
                self._on_preempt_warning(r, float(deadline))
                changed = True
        return changed

    def _on_preempt_warning(self, r: _Replica, deadline_ts: float):
        """A preempt_warning landed on this replica's capacity gang:
        treat it as already-lost capacity and drain inside the grace
        window. When the drain completes pre-fire, ``_kill`` removes the
        warned gang — which cancels the fire (the GCS's controlled-drain
        escape hatch) and returns the capacity to queued gangs."""
        r.warned = True
        r.warn_deadline = deadline_ts
        grace = max(0.05, deadline_ts - time.time())
        reason = "scale_down" if r.drain_requested else "preempted"
        _events.record("SERVE_REPLICA_WARNED", deployment=self.dep_id,
                       replica_id=r.replica_id, job=self.job,
                       reason=reason, grace_s=round(grace, 3))
        _tm.counter_inc("ray_tpu_serve_preempt_drains_total",
                        tags={"deployment": self.dep_id, "reason": reason})
        self._begin_stop(r, deadline_s=grace)

    def _create_capacity_pg(self, slot: int):
        """One-bundle gang claiming this replica's share of the cluster
        in the job plane; named by the slot tag so chaos schedules and
        the controller's own drain requests address the same gang."""
        if not self.job:
            return None, 0.0
        from ray_tpu._private.config import get_config

        opts = self.config.ray_actor_options or {}
        cpu = float(opts.get("num_cpus")
                    or get_config("serve_replica_capacity_cpu"))
        pg_id = os.urandom(16)
        try:
            self._gcs_call("create_placement_group", pg_id=pg_id,
                           bundles=[{"CPU": cpu}], strategy="PACK",
                           name=slot_tag(self.dep_id, slot), job=self.job)
        except Exception:
            return None, 0.0
        return pg_id, time.monotonic()

    def _scale_down_replica(self, r: _Replica):
        """Scale-down for a tenant replica rides the preemption-warning
        machinery (self-preempt narrowed to the victim slot's gang): the
        warning reaches routers and the replica exactly like an external
        preemption, the drain honors the grace window, and the gang is
        removed pre-fire. Kill switch ``serve_preempt_scale_down=0`` (or
        an untenanted app / unplaced gang) falls back to a direct stop."""
        from ray_tpu._private.config import get_config

        if (self.job and r.state == RUNNING and r.pg_created
                and not r.warned
                and int(get_config("serve_preempt_scale_down"))):
            try:
                victim = self._gcs_call(
                    "preempt_job", name=self.job,
                    pg_name=slot_tag(self.dep_id, r.slot))
            except Exception:
                victim = None
            if victim is not None:
                # the warning lands via the capacity poll, which begins
                # the drain; excluded from _live() so the scale loop
                # neither re-picks nor replaces it
                r.drain_requested = True
                return
        self._begin_stop(r)

    def on_actor_death(self, actor_id_hex: str) -> bool:
        """GCS death-feed fast path: drop the dead replica NOW and
        re-broadcast, so routers shed its traffic in milliseconds. The
        scale loop replaces the capacity on its next tick. Returns True
        when the actor was one of this deployment's replicas."""
        for r in list(self.replicas):
            if r.actor_id_hex and r.actor_id_hex == actor_id_hex:
                was_stopping = r.state == STOPPING
                # _kill releases the capacity gang too (the kill on an
                # already-dead handle is a no-op) — dropping the replica
                # without it leaks a CREATED, quota-counted gang whose
                # slot-tag name then collides with the replacement's
                self._kill(r)
                if not was_stopping:
                    _events.record("REPLICA_DIED", deployment=self.dep_id,
                                   replica_id=r.replica_id,
                                   source="death_feed")
                    _tm.counter_inc(
                        "ray_tpu_serve_replica_restarts_total",
                        tags={"deployment": self.dep_id, "reason": "death"})
                self.broadcast()
                self._set_replica_gauges()
                return True
        return False

    def _health_checks(self) -> bool:
        import ray_tpu

        changed = False
        now = time.monotonic()
        for r in list(self.replicas):
            if r.state != RUNNING:
                continue
            if r.health_ref is not None:
                try:
                    done, _ = ray_tpu.wait([r.health_ref], timeout=0)
                except Exception:
                    done = [r.health_ref]
                if done:
                    try:
                        # done ref: timeout bounds only the fetch (a
                        # hang here would freeze every health check)
                        ray_tpu.get(r.health_ref, timeout=10.0)
                        r.health_ref = None
                        r.last_health_check = now
                    except Exception:
                        # failed health check → replace
                        self._drop(r, reason="health")
                        changed = True
                elif now > r.health_deadline:
                    self._drop(r, reason="health")
                    changed = True
            elif (now - r.last_health_check
                    >= self.config.health_check_period_s):
                try:
                    r.health_ref = r.handle.check_health.remote()
                    r.health_deadline = (
                        now + self.config.health_check_timeout_s)
                except Exception:
                    self._drop(r, reason="death")
                    changed = True
        return changed

    def _autoscale(self):
        ac = self.config.autoscaling_config
        if ac is None:
            return
        now = time.monotonic()
        if now - self._last_metrics_poll >= ac.metrics_interval_s:
            self._last_metrics_poll = now
            self._poll_replica_metrics()
        # warned/self-draining replicas are already-lost capacity: they
        # accept no new work, so counting them in `current` would both
        # understate per-replica load and delay the replacement decision
        running = [r for r in self._live() if r.state == RUNNING]
        if not running:
            return
        # Handle-side metrics (queued + in-flight at routers) capture demand
        # the replicas never see when the router caps in-flight; fall back
        # to replica-side ongoing when no router has reported recently.
        # This is the telemetry plane's queue-depth signal — the same
        # number the routers export as ray_tpu_serve_queue_depth_tasks.
        fresh_cutoff = now - 2.0
        # evict long-stale routers (exited drivers/proxies): the
        # controller is detached and outlives them, and each minted a
        # fresh uuid router_id — without pruning this dict grows with
        # every driver that ever touched the deployment
        for rid in [r for r, (_, ts) in self.handle_metrics.items()
                    if ts < now - 30.0]:
            del self.handle_metrics[rid]
        handle_total = sum(v for v, ts in self.handle_metrics.values()
                           if ts >= fresh_cutoff)
        has_fresh = any(ts >= fresh_cutoff
                        for _, ts in self.handle_metrics.values())
        total_ongoing = (handle_total if has_fresh
                         else sum(r.num_ongoing for r in running))
        desired = ac.desired_replicas(len(running), total_ongoing)
        if desired == self.target_num:
            self._scale_proposal_since = None
            return
        delay = (ac.upscale_delay_s if desired > self.target_num
                 else ac.downscale_delay_s)
        prop = self._scale_proposal_since
        if prop is None or prop[0] != desired:
            # hysteresis: a proposal must SUSTAIN for the configured
            # delay before it moves the target (blips don't scale)
            self._scale_proposal_since = (desired, now)
            return
        if now - prop[1] >= delay:
            direction = "up" if desired > self.target_num else "down"
            _events.record("SERVE_SCALED", deployment=self.dep_id,
                           direction=direction,
                           from_replicas=self.target_num,
                           to_replicas=desired,
                           total_ongoing=total_ongoing)
            _tm.counter_inc("ray_tpu_serve_autoscale_total",
                            tags={"deployment": self.dep_id,
                                  "direction": direction})
            self.target_num = desired
            self._scale_proposal_since = None

    def _poll_replica_metrics(self):
        import ray_tpu

        for r in self.replicas:
            if r.state != RUNNING:
                continue
            if r.metrics_ref is not None:
                try:
                    done, _ = ray_tpu.wait([r.metrics_ref], timeout=0)
                    if done:
                        m = ray_tpu.get(r.metrics_ref, timeout=10.0)
                        r.num_ongoing = m["num_ongoing_requests"]
                        r.metrics_ref = None
                except Exception:
                    r.metrics_ref = None
            if r.metrics_ref is None:
                try:
                    r.metrics_ref = r.handle.get_metrics.remote()
                except Exception:
                    pass

    # ------------------------------------------------------------- actions
    def _start_replica(self):
        import ray_tpu
        from ray_tpu.serve._private.replica import ReplicaActor

        rid = f"{self.dep_id}#{uuid.uuid4().hex[:6]}"
        actor_name = f"SERVE_REPLICA::{rid}"
        opts = dict(self.spec["config"].get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 0)
        cap = int(self.config.max_ongoing_requests)
        # stable slot ordinal (lowest unused): a replacement replica
        # inherits the dead one's slot, so seeded chaos schedules can
        # target one slot's lineage (`serve-<dep>-slot0`) and kill a
        # minority of capacity instead of every replica in lockstep
        used = {r.slot for r in self.replicas}
        slot = next(i for i in range(len(self.replicas) + 1)
                    if i not in used)
        pg_id, requested_ts = self._create_capacity_pg(slot)
        # tenant apps: label the replica's actor lease with the job so
        # lease-side usage gossip attributes it to the right tenant
        from ray_tpu.util import jobs as _jobs

        prev_job = _jobs.current_job()
        if self.job:
            _jobs.set_current_job(self.job)
        try:
            handle = ray_tpu.remote(ReplicaActor).options(
                name=actor_name, namespace="serve",
                max_concurrency=cap + 8,  # headroom for health/metrics calls
                max_restarts=0,           # controller replaces, not restarts
                **opts,
            ).remote(self.dep_id, rid, self.spec["user_callable"],
                     self.spec.get("init_args") or (),
                     self.spec.get("init_kwargs") or {},
                     self.config.user_config, slot)
        finally:
            if self.job:
                _jobs.set_current_job(prev_job)
        ready_ref = handle.ready.remote()
        r = _Replica(rid, actor_name, handle, ready_ref, slot)
        r.capacity_pg_id = pg_id
        r.pg_requested_ts = requested_ts
        self.replicas.append(r)

    def _begin_stop(self, r: _Replica, deadline_s: float | None = None):
        """``deadline_s`` caps the drain budget (the preemption grace
        window remaining) — the drain must finish, and the warned gang
        be removed, BEFORE the fire for the controlled-drain no-op."""
        r.state = STOPPING
        budget = self.config.graceful_shutdown_timeout_s
        slack = 1.0
        if deadline_s is not None:
            budget = min(budget, max(0.05, deadline_s - 0.05))
            slack = 0.2
        try:
            r.drain_ref = r.handle.prepare_for_shutdown.remote(budget)
        except Exception:
            r.drain_ref = None
        r.drain_deadline = time.monotonic() + budget + slack

    def _drop(self, r: _Replica, reason: str = "death"):
        """Immediate removal (failed init / failed health check)."""
        _events.record("REPLICA_DIED", deployment=self.dep_id,
                       replica_id=r.replica_id, source=reason)
        _tm.counter_inc("ray_tpu_serve_replica_restarts_total",
                        tags={"deployment": self.dep_id, "reason": reason})
        self._kill(r)

    def _kill(self, r: _Replica):
        import ray_tpu

        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass
        if r.capacity_pg_id is not None:
            # release the capacity claim: removing a warned gang
            # PRE-FIRE no-ops the pending fire, and either way
            # _maybe_schedule_pending(force) hands the freed capacity to
            # queued gangs (the training job resumes here)
            try:
                self._gcs_call("remove_placement_group",
                               pg_id=r.capacity_pg_id)
            except Exception:
                pass
            r.capacity_pg_id = None
        if r in self.replicas:
            self.replicas.remove(r)

    # ------------------------------------------------------------ broadcast
    def broadcast(self):
        entries = [{"replica_id": r.replica_id, "actor_name": r.actor_name,
                    "actor_id": r.actor_id_hex}
                   for r in self.replicas if r.state == RUNNING]
        # draining replicas (scale-down or preemption-warned): routers
        # drop them from selection proactively and use the latest drain
        # deadline as the shed retry-after hint (wall-clock so it
        # crosses processes)
        now_wall, now_mono = time.time(), time.monotonic()
        draining = [{"replica_id": r.replica_id,
                     "deadline_ts": (r.warn_deadline if r.warn_deadline
                                     else now_wall + max(
                                         0.0, (r.drain_deadline or now_mono)
                                         - now_mono))}
                    for r in self.replicas if r.state == STOPPING]
        self.host.notify_changed(
            replicas_key(self.dep_id),
            {"replicas": entries,
             "draining": draining,
             "max_ongoing_requests": self.config.max_ongoing_requests,
             "max_queued_requests": self.config.max_queued_requests})

    def _set_replica_gauges(self):
        counts = {s: 0 for s in (STARTING, RUNNING, STOPPING)}
        warned = 0
        for r in self.replicas:
            counts[r.state] = counts.get(r.state, 0) + 1
            warned += bool(r.warned)
        for state, n in counts.items():
            _tm.gauge_set("ray_tpu_serve_replicas_tasks", n,
                          tags={"deployment": self.dep_id,
                                "state": state.lower()})
        _tm.gauge_set("ray_tpu_serve_replicas_tasks",
                      0 if self.deleting else self.target_num,
                      tags={"deployment": self.dep_id, "state": "target"})
        _tm.gauge_set("ray_tpu_serve_warned_replicas_tasks", warned,
                      tags={"deployment": self.dep_id})

    def status(self) -> dict:
        return {
            "name": self.spec["name"],
            "status": ("DELETING" if self.deleting else
                       "HEALTHY" if self._num_running() >= self.target_num
                       else "UPDATING"),
            "target_num_replicas": self.target_num,
            "job": self.job,
            "replica_states": {
                s: sum(1 for r in self.replicas if r.state == s)
                for s in (STARTING, RUNNING, STOPPING)},
            "warned_replicas": sum(1 for r in self.replicas if r.warned),
        }

    def _num_running(self):
        return sum(1 for r in self.replicas if r.state == RUNNING)


class ServeController:
    """The detached controller actor (reference: controller.py:61)."""

    def __init__(self, http_options: dict | None = None):
        self._host = LongPollHost()
        self._lock = threading.RLock()
        self._deployments: dict[str, _DeploymentState] = {}
        self._apps: dict[str, dict] = {}      # name → {route_prefix, ingress}
        self._http_options = http_options or {}
        self._shutdown = threading.Event()
        self._death_watch = self._start_death_watch()
        self._loop = threading.Thread(target=self._run_control_loop,
                                      daemon=True, name="serve-controller")
        self._loop.start()

    def _start_death_watch(self):
        """GCS actor-death subscription: replica death reaches the FSM in
        the feed's publish latency, not a health-check period. Best-effort
        (None without a worker runtime — the health checks still catch
        everything, just slower)."""
        try:
            from ray_tpu._private.pubsub import watch_actor_deaths

            return watch_actor_deaths(self._on_actor_death)
        except Exception:
            return None

    def _on_actor_death(self, actor_id, reason: str):
        hex_id = actor_id.hex() if isinstance(actor_id, bytes) else actor_id
        with self._lock:
            for ds in self._deployments.values():
                if ds.on_actor_death(hex_id):
                    return

    # ------------------------------------------------------------- RPC API
    def listen_for_change(self, snapshot_ids: dict):
        return self._host.listen_for_change(snapshot_ids)

    def get_http_options(self) -> dict:
        return self._http_options

    def deploy_application(self, app_spec: dict):
        """app_spec: {name, route_prefix, ingress, deployments: [dep specs],
        job?, job_quota?, job_priority?}. Each dep spec: {name,
        user_callable, init_args, init_kwargs, config, version}.

        ``job`` makes the app a first-class tenant: the controller
        registers it in the job plane (idempotent — quota/priority update
        in place on redeploy) and every replica's capacity rides a
        job-labeled gang."""
        with self._lock:
            name = app_spec["name"]
            job = str(app_spec.get("job") or "")
            if job:
                try:
                    _worker_gcs_call(
                        "register_job", name=job,
                        quota=app_spec.get("job_quota"),
                        priority=app_spec.get("job_priority"))
                    _events.record("SERVE_APP_REGISTERED", app=name,
                                   job=job,
                                   priority=app_spec.get("job_priority"),
                                   quota=app_spec.get("job_quota"))
                except Exception:
                    # degraded (no job plane): the app still runs, its
                    # gangs carry the label with default policy
                    pass
            new_deps = {}
            for dep in app_spec["deployments"]:
                dep_id = make_dep_id(name, dep["name"])
                new_deps[dep_id] = dep
            # remove deployments dropped from the app
            old = self._apps.get(name)
            if old:
                for dep_id in old["deployment_ids"]:
                    if dep_id not in new_deps:
                        ds = self._deployments.get(dep_id)
                        if ds:
                            ds.mark_deleting()
            for dep_id, dep in new_deps.items():
                if dep_id in self._deployments and \
                        not self._deployments[dep_id].deleting:
                    self._deployments[dep_id].update_spec(dep)
                    self._deployments[dep_id].job = job
                else:
                    self._deployments[dep_id] = _DeploymentState(
                        dep_id, dep, self._host, job=job)
                self._deployments[dep_id].broadcast()
            self._apps[name] = {
                "route_prefix": app_spec.get("route_prefix"),
                "ingress": make_dep_id(name, app_spec["ingress"]),
                "deployment_ids": list(new_deps),
                "job": job,
            }
            self._broadcast_routes()
        return True

    def delete_application(self, name: str):
        with self._lock:
            app = self._apps.pop(name, None)
            if not app:
                return False
            for dep_id in app["deployment_ids"]:
                ds = self._deployments.get(dep_id)
                if ds:
                    ds.mark_deleting()
            self._broadcast_routes()
        return True

    def get_app_status(self, name: str | None = None) -> dict:
        with self._lock:
            out = {}
            for app_name, app in self._apps.items():
                if name is not None and app_name != name:
                    continue
                deps = {}
                for dep_id in app["deployment_ids"]:
                    ds = self._deployments.get(dep_id)
                    if ds:
                        deps[ds.spec["name"]] = ds.status()
                states = [d["status"] for d in deps.values()]
                out[app_name] = {
                    "route_prefix": app["route_prefix"],
                    "ingress": app["ingress"],
                    "job": app.get("job", ""),
                    "status": ("RUNNING" if states and
                               all(s == "HEALTHY" for s in states)
                               else "DEPLOYING"),
                    "deployments": deps,
                }
            return out

    def record_handle_metrics(self, dep_id: str, router_id: str,
                              num_requests: float):
        """Routers push (queued + in-flight) demand for autoscaling."""
        with self._lock:
            ds = self._deployments.get(dep_id)
            if ds is not None:
                ds.handle_metrics[router_id] = (num_requests,
                                                time.monotonic())
        return True

    def get_deployment_info(self, dep_id: str) -> dict | None:
        with self._lock:
            ds = self._deployments.get(dep_id)
            if ds is None:
                return None
            return {"max_ongoing_requests":
                        ds.config.max_ongoing_requests,
                    "max_queued_requests":
                        ds.config.max_queued_requests,
                    "status": ds.status()}

    def graceful_shutdown(self):
        with self._lock:
            for name in list(self._apps):
                self.delete_application(name)
        # wait for replicas to drain out via the control loop
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with self._lock:
                if not self._deployments:
                    break
            time.sleep(0.05)
        self._shutdown.set()
        watch, self._death_watch = self._death_watch, None
        if watch is not None:
            try:
                watch.stop()
            except Exception:
                pass
        return True

    # ------------------------------------------------------------ internals
    def _broadcast_routes(self):
        routes = {}
        for app_name, app in self._apps.items():
            if app.get("route_prefix"):
                routes[app["route_prefix"]] = {
                    "app_name": app_name,
                    "ingress_deployment": app["ingress"],
                }
        self._host.notify_changed(ROUTE_TABLE_KEY, routes)

    def _run_control_loop(self):
        while not self._shutdown.is_set():
            try:
                with self._lock:
                    for dep_id, ds in list(self._deployments.items()):
                        finished = ds.reconcile()
                        if finished:
                            del self._deployments[dep_id]
                            self._host.drop_key(replicas_key(dep_id))
            except Exception:
                import traceback

                traceback.print_exc()
            self._shutdown.wait(RECONCILE_PERIOD_S)

    def ready(self):
        return True
