"""ServeController actor: owns all deployment state and reconciles it.

Reference: python/ray/serve/controller.py:61 (ServeController),
serve/_private/deployment_state.py:958 (DeploymentState FSM; scale loop at
:1281,1623; ActorReplicaWrapper at :168) and
serve/_private/autoscaling_policy.py. One detached named actor; a
background thread runs the reconcile loop:

    target state (app specs) ──reconcile──▶ replica actors
                                     │
                 long-poll push ◀────┘  (routers/proxies learn replica sets)

Replica FSM: STARTING ─ready──▶ RUNNING ─drain──▶ STOPPING ─▶ gone; a
failed health check or dead actor re-enters through STARTING via a fresh
replica (replicas are cattle — same as the reference).
"""
from __future__ import annotations

import threading
import time
import uuid

from ray_tpu.serve._private.constants import (
    ROUTE_TABLE_KEY,
    deployment_id as make_dep_id,
    replicas_key,
)
from ray_tpu.serve._private.long_poll import LongPollHost
from ray_tpu.serve.config import DeploymentConfig

STARTING, RUNNING, STOPPING = "STARTING", "RUNNING", "STOPPING"
RECONCILE_PERIOD_S = 0.1


class _Replica:
    def __init__(self, replica_id, actor_name, handle, ready_ref):
        self.replica_id = replica_id
        self.actor_name = actor_name
        self.handle = handle
        self.state = STARTING
        self.ready_ref = ready_ref
        self.drain_ref = None
        self.drain_deadline = None
        self.health_ref = None
        self.health_deadline = None
        self.last_health_check = time.monotonic()
        self.metrics_ref = None
        self.num_ongoing = 0.0


class _DeploymentState:
    """Target + actual state for one deployment."""

    def __init__(self, dep_id: str, spec: dict, host: LongPollHost):
        self.dep_id = dep_id
        self.spec = spec                       # user_callable/init args/...
        self.config = DeploymentConfig.from_dict(spec["config"])
        self.host = host
        self.replicas: list[_Replica] = []
        self.deleting = False
        self.version = spec.get("version") or "1"
        # autoscaling bookkeeping
        ac = self.config.autoscaling_config
        self.target_num = (ac.min_replicas if ac
                           else self.config.num_replicas)
        self._scale_proposal_since: tuple[int, float] | None = None
        self._last_metrics_poll = 0.0
        # handle-side demand: {router_id: (queued+in_flight, monotonic ts)}
        self.handle_metrics: dict[str, tuple[float, float]] = {}

    # ---------------------------------------------------------- target edit
    def update_spec(self, spec: dict):
        old_config = self.config
        self.spec = spec
        self.config = DeploymentConfig.from_dict(spec["config"])
        new_version = spec.get("version") or "1"
        code_changed = new_version != self.version
        self.version = new_version
        ac = self.config.autoscaling_config
        if ac:
            self.target_num = max(ac.min_replicas,
                                  min(ac.max_replicas, self.target_num))
        else:
            self.target_num = self.config.num_replicas
        if code_changed:
            # roll every replica (simple stop-all; the reference does a
            # gradual rolling update — acceptable simplification, the FSM
            # recreates capacity on the next ticks)
            for r in self.replicas:
                if r.state != STOPPING:
                    self._begin_stop(r)
        elif old_config.user_config != self.config.user_config:
            for r in self.replicas:
                if r.state == RUNNING:
                    try:
                        r.handle.reconfigure.remote(self.config.user_config)
                    except Exception:
                        pass

    def mark_deleting(self):
        self.deleting = True
        for r in self.replicas:
            if r.state != STOPPING:
                self._begin_stop(r)

    # ------------------------------------------------------------ reconcile
    def reconcile(self) -> bool:
        """One tick. Returns True when (deleting and fully stopped)."""
        import ray_tpu

        changed = False
        # 1. STARTING → RUNNING when ready_ref resolves
        for r in self.replicas:
            if r.state == STARTING:
                try:
                    done, _ = ray_tpu.wait([r.ready_ref], timeout=0)
                except Exception:
                    done = []
                if done:
                    try:
                        ray_tpu.get(r.ready_ref)   # surface init errors
                        r.state = RUNNING
                        changed = True
                    except Exception:
                        self._drop(r)
                        changed = True
        # 2. reap STOPPING
        for r in list(self.replicas):
            if r.state == STOPPING:
                drained = False
                if r.drain_ref is not None:
                    try:
                        done, _ = ray_tpu.wait([r.drain_ref], timeout=0)
                        drained = bool(done)
                    except Exception:
                        drained = True
                if drained or time.monotonic() > r.drain_deadline:
                    self._kill(r)
                    changed = True
        if self.deleting:
            return not self.replicas
        # 3. health checks on RUNNING
        changed |= self._health_checks()
        # 4. autoscaling metrics + decision
        self._autoscale()
        # 5. scale toward target
        live = [r for r in self.replicas if r.state in (STARTING, RUNNING)]
        if len(live) < self.target_num:
            for _ in range(self.target_num - len(live)):
                self._start_replica()
            changed = True
        elif len(live) > self.target_num:
            # stop youngest first (prefer keeping warmed replicas)
            extra = len(live) - self.target_num
            for r in reversed(live):
                if extra == 0:
                    break
                if r.state == STARTING or r.state == RUNNING:
                    self._begin_stop(r)
                    extra -= 1
            changed = True
        if changed:
            self.broadcast()
        return False

    def _health_checks(self) -> bool:
        import ray_tpu

        changed = False
        now = time.monotonic()
        for r in list(self.replicas):
            if r.state != RUNNING:
                continue
            if r.health_ref is not None:
                try:
                    done, _ = ray_tpu.wait([r.health_ref], timeout=0)
                except Exception:
                    done = [r.health_ref]
                if done:
                    try:
                        ray_tpu.get(r.health_ref)
                        r.health_ref = None
                        r.last_health_check = now
                    except Exception:
                        # failed health check → replace
                        self._drop(r)
                        changed = True
                elif now > r.health_deadline:
                    self._drop(r)
                    changed = True
            elif (now - r.last_health_check
                    >= self.config.health_check_period_s):
                try:
                    r.health_ref = r.handle.check_health.remote()
                    r.health_deadline = (
                        now + self.config.health_check_timeout_s)
                except Exception:
                    self._drop(r)
                    changed = True
        return changed

    def _autoscale(self):
        import ray_tpu

        ac = self.config.autoscaling_config
        if ac is None:
            return
        now = time.monotonic()
        if now - self._last_metrics_poll >= ac.metrics_interval_s:
            self._last_metrics_poll = now
            for r in self.replicas:
                if r.state != RUNNING:
                    continue
                if r.metrics_ref is not None:
                    try:
                        done, _ = ray_tpu.wait([r.metrics_ref], timeout=0)
                        if done:
                            m = ray_tpu.get(r.metrics_ref)
                            r.num_ongoing = m["num_ongoing_requests"]
                            r.metrics_ref = None
                    except Exception:
                        r.metrics_ref = None
                if r.metrics_ref is None:
                    try:
                        r.metrics_ref = r.handle.get_metrics.remote()
                    except Exception:
                        pass
        running = [r for r in self.replicas if r.state == RUNNING]
        if not running:
            return
        # Handle-side metrics (queued + in-flight at routers) capture demand
        # the replicas never see when the router caps in-flight; fall back
        # to replica-side ongoing when no router has reported recently.
        fresh_cutoff = now - 2.0
        handle_total = sum(v for v, ts in self.handle_metrics.values()
                           if ts >= fresh_cutoff)
        has_fresh = any(ts >= fresh_cutoff
                        for _, ts in self.handle_metrics.values())
        total_ongoing = (handle_total if has_fresh
                         else sum(r.num_ongoing for r in running))
        desired = ac.desired_replicas(len(running), total_ongoing)
        if desired == self.target_num:
            self._scale_proposal_since = None
            return
        delay = (ac.upscale_delay_s if desired > self.target_num
                 else ac.downscale_delay_s)
        prop = self._scale_proposal_since
        if prop is None or prop[0] != desired:
            self._scale_proposal_since = (desired, now)
            return
        if now - prop[1] >= delay:
            self.target_num = desired
            self._scale_proposal_since = None

    # ------------------------------------------------------------- actions
    def _start_replica(self):
        import ray_tpu
        from ray_tpu.serve._private.replica import ReplicaActor

        rid = f"{self.dep_id}#{uuid.uuid4().hex[:6]}"
        actor_name = f"SERVE_REPLICA::{rid}"
        opts = dict(self.spec["config"].get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 0)
        cap = int(self.config.max_ongoing_requests)
        handle = ray_tpu.remote(ReplicaActor).options(
            name=actor_name, namespace="serve",
            max_concurrency=cap + 8,    # headroom for health/metrics calls
            max_restarts=0,             # controller replaces, not restarts
            **opts,
        ).remote(self.dep_id, rid, self.spec["user_callable"],
                 self.spec.get("init_args") or (),
                 self.spec.get("init_kwargs") or {},
                 self.config.user_config)
        ready_ref = handle.ready.remote()
        self.replicas.append(_Replica(rid, actor_name, handle, ready_ref))

    def _begin_stop(self, r: _Replica):
        r.state = STOPPING
        try:
            r.drain_ref = r.handle.prepare_for_shutdown.remote(
                self.config.graceful_shutdown_timeout_s)
        except Exception:
            r.drain_ref = None
        r.drain_deadline = (time.monotonic()
                            + self.config.graceful_shutdown_timeout_s + 1.0)

    def _drop(self, r: _Replica):
        """Immediate removal (failed init / failed health check)."""
        self._kill(r)

    def _kill(self, r: _Replica):
        import ray_tpu

        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass
        if r in self.replicas:
            self.replicas.remove(r)

    # ------------------------------------------------------------ broadcast
    def broadcast(self):
        entries = [{"replica_id": r.replica_id, "actor_name": r.actor_name}
                   for r in self.replicas if r.state == RUNNING]
        self.host.notify_changed(
            replicas_key(self.dep_id),
            {"replicas": entries,
             "max_ongoing_requests": self.config.max_ongoing_requests})

    def status(self) -> dict:
        return {
            "name": self.spec["name"],
            "status": ("DELETING" if self.deleting else
                       "HEALTHY" if self._num_running() >= self.target_num
                       else "UPDATING"),
            "target_num_replicas": self.target_num,
            "replica_states": {
                s: sum(1 for r in self.replicas if r.state == s)
                for s in (STARTING, RUNNING, STOPPING)},
        }

    def _num_running(self):
        return sum(1 for r in self.replicas if r.state == RUNNING)


class ServeController:
    """The detached controller actor (reference: controller.py:61)."""

    def __init__(self, http_options: dict | None = None):
        self._host = LongPollHost()
        self._lock = threading.RLock()
        self._deployments: dict[str, _DeploymentState] = {}
        self._apps: dict[str, dict] = {}      # name → {route_prefix, ingress}
        self._http_options = http_options or {}
        self._shutdown = threading.Event()
        self._loop = threading.Thread(target=self._run_control_loop,
                                      daemon=True, name="serve-controller")
        self._loop.start()

    # ------------------------------------------------------------- RPC API
    def listen_for_change(self, snapshot_ids: dict):
        return self._host.listen_for_change(snapshot_ids)

    def get_http_options(self) -> dict:
        return self._http_options

    def deploy_application(self, app_spec: dict):
        """app_spec: {name, route_prefix, ingress, deployments: [dep specs]}
        Each dep spec: {name, user_callable, init_args, init_kwargs, config,
        version}."""
        with self._lock:
            name = app_spec["name"]
            new_deps = {}
            for dep in app_spec["deployments"]:
                dep_id = make_dep_id(name, dep["name"])
                new_deps[dep_id] = dep
            # remove deployments dropped from the app
            old = self._apps.get(name)
            if old:
                for dep_id in old["deployment_ids"]:
                    if dep_id not in new_deps:
                        ds = self._deployments.get(dep_id)
                        if ds:
                            ds.mark_deleting()
            for dep_id, dep in new_deps.items():
                if dep_id in self._deployments and \
                        not self._deployments[dep_id].deleting:
                    self._deployments[dep_id].update_spec(dep)
                else:
                    self._deployments[dep_id] = _DeploymentState(
                        dep_id, dep, self._host)
                self._deployments[dep_id].broadcast()
            self._apps[name] = {
                "route_prefix": app_spec.get("route_prefix"),
                "ingress": make_dep_id(name, app_spec["ingress"]),
                "deployment_ids": list(new_deps),
            }
            self._broadcast_routes()
        return True

    def delete_application(self, name: str):
        with self._lock:
            app = self._apps.pop(name, None)
            if not app:
                return False
            for dep_id in app["deployment_ids"]:
                ds = self._deployments.get(dep_id)
                if ds:
                    ds.mark_deleting()
            self._broadcast_routes()
        return True

    def get_app_status(self, name: str | None = None) -> dict:
        with self._lock:
            out = {}
            for app_name, app in self._apps.items():
                if name is not None and app_name != name:
                    continue
                deps = {}
                for dep_id in app["deployment_ids"]:
                    ds = self._deployments.get(dep_id)
                    if ds:
                        deps[ds.spec["name"]] = ds.status()
                states = [d["status"] for d in deps.values()]
                out[app_name] = {
                    "route_prefix": app["route_prefix"],
                    "ingress": app["ingress"],
                    "status": ("RUNNING" if states and
                               all(s == "HEALTHY" for s in states)
                               else "DEPLOYING"),
                    "deployments": deps,
                }
            return out

    def record_handle_metrics(self, dep_id: str, router_id: str,
                              num_requests: float):
        """Routers push (queued + in-flight) demand for autoscaling."""
        with self._lock:
            ds = self._deployments.get(dep_id)
            if ds is not None:
                ds.handle_metrics[router_id] = (num_requests,
                                                time.monotonic())
        return True

    def get_deployment_info(self, dep_id: str) -> dict | None:
        with self._lock:
            ds = self._deployments.get(dep_id)
            if ds is None:
                return None
            return {"max_ongoing_requests":
                        ds.config.max_ongoing_requests,
                    "status": ds.status()}

    def graceful_shutdown(self):
        with self._lock:
            for name in list(self._apps):
                self.delete_application(name)
        # wait for replicas to drain out via the control loop
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with self._lock:
                if not self._deployments:
                    break
            time.sleep(0.05)
        self._shutdown.set()
        return True

    # ------------------------------------------------------------ internals
    def _broadcast_routes(self):
        routes = {}
        for app_name, app in self._apps.items():
            if app.get("route_prefix"):
                routes[app["route_prefix"]] = {
                    "app_name": app_name,
                    "ingress_deployment": app["ingress"],
                }
        self._host.notify_changed(ROUTE_TABLE_KEY, routes)

    def _run_control_loop(self):
        while not self._shutdown.is_set():
            try:
                with self._lock:
                    for dep_id, ds in list(self._deployments.items()):
                        finished = ds.reconcile()
                        if finished:
                            del self._deployments[dep_id]
                            self._host.drop_key(replicas_key(dep_id))
            except Exception:
                import traceback

                traceback.print_exc()
            self._shutdown.wait(RECONCILE_PERIOD_S)

    def ready(self):
        return True
