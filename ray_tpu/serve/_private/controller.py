"""ServeController actor: owns all deployment state and reconciles it.

Reference: python/ray/serve/controller.py:61 (ServeController),
serve/_private/deployment_state.py:958 (DeploymentState FSM; scale loop at
:1281,1623; ActorReplicaWrapper at :168) and
serve/_private/autoscaling_policy.py. One detached named actor; a
background thread runs the reconcile loop:

    target state (app specs) ──reconcile──▶ replica actors
                                     │
                 long-poll push ◀────┘  (routers/proxies learn replica sets)

Replica FSM: STARTING ─ready──▶ RUNNING ─drain──▶ STOPPING ─▶ gone; a
failed health check or dead actor re-enters through STARTING via a fresh
replica (replicas are cattle — same as the reference).

Fast failure detection: besides per-replica health checks (period
``health_check_period_s``), the controller subscribes to the GCS
actor-death feed (PR 5's ``watch_actor_deaths``). A dead replica is
dropped and re-broadcast within the feed's publish latency — routers
stop routing to it in milliseconds, and the scale loop starts the
replacement on the next tick instead of a health-check period later.

Observability: replica lifecycle lands in the cluster event log
(``REPLICA_STARTED`` / ``REPLICA_DIED`` / ``REPLICA_DRAINED``), autoscale
decisions as ``SERVE_SCALED``; the metric catalog carries the FSM
occupancy gauge (``ray_tpu_serve_replicas_tasks``), replacement counters
(``ray_tpu_serve_replica_restarts_total{reason}``) and autoscale
decisions (``ray_tpu_serve_autoscale_total{direction}``).
"""
from __future__ import annotations

import threading
import time
import uuid

from ray_tpu._private import events as _events
from ray_tpu._private import telemetry as _tm
from ray_tpu.serve._private.constants import (
    ROUTE_TABLE_KEY,
    deployment_id as make_dep_id,
    replicas_key,
)
from ray_tpu.serve._private.long_poll import LongPollHost
from ray_tpu.serve.config import DeploymentConfig

STARTING, RUNNING, STOPPING = "STARTING", "RUNNING", "STOPPING"
RECONCILE_PERIOD_S = 0.1


class _Replica:
    def __init__(self, replica_id, actor_name, handle, ready_ref,
                 slot: int = 0):
        self.replica_id = replica_id
        self.actor_name = actor_name
        self.handle = handle
        self.slot = slot
        self.actor_id_hex = getattr(handle, "_actor_id", b"").hex()
        self.state = STARTING
        self.ready_ref = ready_ref
        self.drain_ref = None
        self.drain_deadline = None
        self.health_ref = None
        self.health_deadline = None
        self.last_health_check = time.monotonic()
        self.metrics_ref = None
        self.num_ongoing = 0.0


class _DeploymentState:
    """Target + actual state for one deployment."""

    def __init__(self, dep_id: str, spec: dict, host: LongPollHost):
        self.dep_id = dep_id
        self.spec = spec                       # user_callable/init args/...
        self.config = DeploymentConfig.from_dict(spec["config"])
        self.host = host
        self.replicas: list[_Replica] = []
        self.deleting = False
        self.version = spec.get("version") or "1"
        # autoscaling bookkeeping
        ac = self.config.autoscaling_config
        self.target_num = (ac.min_replicas if ac
                           else self.config.num_replicas)
        self._scale_proposal_since: tuple[int, float] | None = None
        self._last_metrics_poll = 0.0
        # handle-side demand: {router_id: (queued+in_flight, monotonic ts)}
        self.handle_metrics: dict[str, tuple[float, float]] = {}

    # ---------------------------------------------------------- target edit
    def update_spec(self, spec: dict):
        old_config = self.config
        self.spec = spec
        self.config = DeploymentConfig.from_dict(spec["config"])
        new_version = spec.get("version") or "1"
        code_changed = new_version != self.version
        self.version = new_version
        ac = self.config.autoscaling_config
        if ac:
            self.target_num = max(ac.min_replicas,
                                  min(ac.max_replicas, self.target_num))
        else:
            self.target_num = self.config.num_replicas
        if code_changed:
            # roll every replica (simple stop-all; the reference does a
            # gradual rolling update — acceptable simplification, the FSM
            # recreates capacity on the next ticks)
            for r in self.replicas:
                if r.state != STOPPING:
                    self._begin_stop(r)
        elif old_config.user_config != self.config.user_config:
            for r in self.replicas:
                if r.state == RUNNING:
                    try:
                        r.handle.reconfigure.remote(self.config.user_config)
                    except Exception:
                        pass

    def mark_deleting(self):
        self.deleting = True
        for r in self.replicas:
            if r.state != STOPPING:
                self._begin_stop(r)

    # ------------------------------------------------------------ reconcile
    def reconcile(self) -> bool:
        """One tick. Returns True when (deleting and fully stopped)."""
        import ray_tpu

        changed = False
        # 1. STARTING → RUNNING when ready_ref resolves
        for r in self.replicas:
            if r.state == STARTING:
                try:
                    done, _ = ray_tpu.wait([r.ready_ref], timeout=0)
                except Exception:
                    done = []
                if done:
                    try:
                        # surface init errors; the ref is already done
                        # (wait above), so the timeout only bounds the
                        # result fetch — timeout-less, a wedged store
                        # fetch would stall the whole control loop
                        # under the controller lock (raylint RTL102)
                        ray_tpu.get(r.ready_ref, timeout=10.0)
                        r.state = RUNNING
                        _events.record("REPLICA_STARTED",
                                       deployment=self.dep_id,
                                       replica_id=r.replica_id)
                        changed = True
                    except Exception:
                        self._drop(r, reason="init")
                        changed = True
        # 2. reap STOPPING
        for r in list(self.replicas):
            if r.state == STOPPING:
                drained = False
                if r.drain_ref is not None:
                    try:
                        done, _ = ray_tpu.wait([r.drain_ref], timeout=0)
                        drained = bool(done)
                    except Exception:
                        drained = True
                if drained or time.monotonic() > r.drain_deadline:
                    _events.record("REPLICA_DRAINED",
                                   deployment=self.dep_id,
                                   replica_id=r.replica_id,
                                   graceful=drained)
                    self._kill(r)
                    changed = True
        if self.deleting:
            if not self.replicas:
                self._set_replica_gauges()
                return True
            return False
        # 3. health checks on RUNNING
        changed |= self._health_checks()
        # 4. autoscaling metrics + decision
        self._autoscale()
        # 5. scale toward target
        live = [r for r in self.replicas if r.state in (STARTING, RUNNING)]
        if len(live) < self.target_num:
            for _ in range(self.target_num - len(live)):
                self._start_replica()
            changed = True
        elif len(live) > self.target_num:
            # stop youngest first (prefer keeping warmed replicas)
            extra = len(live) - self.target_num
            for r in reversed(live):
                if extra == 0:
                    break
                if r.state == STARTING or r.state == RUNNING:
                    self._begin_stop(r)
                    extra -= 1
            changed = True
        if changed:
            self.broadcast()
            self._set_replica_gauges()
        return False

    def on_actor_death(self, actor_id_hex: str) -> bool:
        """GCS death-feed fast path: drop the dead replica NOW and
        re-broadcast, so routers shed its traffic in milliseconds. The
        scale loop replaces the capacity on its next tick. Returns True
        when the actor was one of this deployment's replicas."""
        for r in list(self.replicas):
            if r.actor_id_hex and r.actor_id_hex == actor_id_hex:
                was_stopping = r.state == STOPPING
                if r in self.replicas:
                    self.replicas.remove(r)
                if not was_stopping:
                    _events.record("REPLICA_DIED", deployment=self.dep_id,
                                   replica_id=r.replica_id,
                                   source="death_feed")
                    _tm.counter_inc(
                        "ray_tpu_serve_replica_restarts_total",
                        tags={"deployment": self.dep_id, "reason": "death"})
                self.broadcast()
                self._set_replica_gauges()
                return True
        return False

    def _health_checks(self) -> bool:
        import ray_tpu

        changed = False
        now = time.monotonic()
        for r in list(self.replicas):
            if r.state != RUNNING:
                continue
            if r.health_ref is not None:
                try:
                    done, _ = ray_tpu.wait([r.health_ref], timeout=0)
                except Exception:
                    done = [r.health_ref]
                if done:
                    try:
                        # done ref: timeout bounds only the fetch (a
                        # hang here would freeze every health check)
                        ray_tpu.get(r.health_ref, timeout=10.0)
                        r.health_ref = None
                        r.last_health_check = now
                    except Exception:
                        # failed health check → replace
                        self._drop(r, reason="health")
                        changed = True
                elif now > r.health_deadline:
                    self._drop(r, reason="health")
                    changed = True
            elif (now - r.last_health_check
                    >= self.config.health_check_period_s):
                try:
                    r.health_ref = r.handle.check_health.remote()
                    r.health_deadline = (
                        now + self.config.health_check_timeout_s)
                except Exception:
                    self._drop(r, reason="death")
                    changed = True
        return changed

    def _autoscale(self):
        ac = self.config.autoscaling_config
        if ac is None:
            return
        now = time.monotonic()
        if now - self._last_metrics_poll >= ac.metrics_interval_s:
            self._last_metrics_poll = now
            self._poll_replica_metrics()
        running = [r for r in self.replicas if r.state == RUNNING]
        if not running:
            return
        # Handle-side metrics (queued + in-flight at routers) capture demand
        # the replicas never see when the router caps in-flight; fall back
        # to replica-side ongoing when no router has reported recently.
        # This is the telemetry plane's queue-depth signal — the same
        # number the routers export as ray_tpu_serve_queue_depth_tasks.
        fresh_cutoff = now - 2.0
        # evict long-stale routers (exited drivers/proxies): the
        # controller is detached and outlives them, and each minted a
        # fresh uuid router_id — without pruning this dict grows with
        # every driver that ever touched the deployment
        for rid in [r for r, (_, ts) in self.handle_metrics.items()
                    if ts < now - 30.0]:
            del self.handle_metrics[rid]
        handle_total = sum(v for v, ts in self.handle_metrics.values()
                           if ts >= fresh_cutoff)
        has_fresh = any(ts >= fresh_cutoff
                        for _, ts in self.handle_metrics.values())
        total_ongoing = (handle_total if has_fresh
                         else sum(r.num_ongoing for r in running))
        desired = ac.desired_replicas(len(running), total_ongoing)
        if desired == self.target_num:
            self._scale_proposal_since = None
            return
        delay = (ac.upscale_delay_s if desired > self.target_num
                 else ac.downscale_delay_s)
        prop = self._scale_proposal_since
        if prop is None or prop[0] != desired:
            # hysteresis: a proposal must SUSTAIN for the configured
            # delay before it moves the target (blips don't scale)
            self._scale_proposal_since = (desired, now)
            return
        if now - prop[1] >= delay:
            direction = "up" if desired > self.target_num else "down"
            _events.record("SERVE_SCALED", deployment=self.dep_id,
                           direction=direction,
                           from_replicas=self.target_num,
                           to_replicas=desired,
                           total_ongoing=total_ongoing)
            _tm.counter_inc("ray_tpu_serve_autoscale_total",
                            tags={"deployment": self.dep_id,
                                  "direction": direction})
            self.target_num = desired
            self._scale_proposal_since = None

    def _poll_replica_metrics(self):
        import ray_tpu

        for r in self.replicas:
            if r.state != RUNNING:
                continue
            if r.metrics_ref is not None:
                try:
                    done, _ = ray_tpu.wait([r.metrics_ref], timeout=0)
                    if done:
                        m = ray_tpu.get(r.metrics_ref, timeout=10.0)
                        r.num_ongoing = m["num_ongoing_requests"]
                        r.metrics_ref = None
                except Exception:
                    r.metrics_ref = None
            if r.metrics_ref is None:
                try:
                    r.metrics_ref = r.handle.get_metrics.remote()
                except Exception:
                    pass

    # ------------------------------------------------------------- actions
    def _start_replica(self):
        import ray_tpu
        from ray_tpu.serve._private.replica import ReplicaActor

        rid = f"{self.dep_id}#{uuid.uuid4().hex[:6]}"
        actor_name = f"SERVE_REPLICA::{rid}"
        opts = dict(self.spec["config"].get("ray_actor_options") or {})
        opts.setdefault("num_cpus", 0)
        cap = int(self.config.max_ongoing_requests)
        # stable slot ordinal (lowest unused): a replacement replica
        # inherits the dead one's slot, so seeded chaos schedules can
        # target one slot's lineage (`serve-<dep>-slot0`) and kill a
        # minority of capacity instead of every replica in lockstep
        used = {r.slot for r in self.replicas}
        slot = next(i for i in range(len(self.replicas) + 1)
                    if i not in used)
        handle = ray_tpu.remote(ReplicaActor).options(
            name=actor_name, namespace="serve",
            max_concurrency=cap + 8,    # headroom for health/metrics calls
            max_restarts=0,             # controller replaces, not restarts
            **opts,
        ).remote(self.dep_id, rid, self.spec["user_callable"],
                 self.spec.get("init_args") or (),
                 self.spec.get("init_kwargs") or {},
                 self.config.user_config, slot)
        ready_ref = handle.ready.remote()
        self.replicas.append(_Replica(rid, actor_name, handle, ready_ref,
                                      slot))

    def _begin_stop(self, r: _Replica):
        r.state = STOPPING
        try:
            r.drain_ref = r.handle.prepare_for_shutdown.remote(
                self.config.graceful_shutdown_timeout_s)
        except Exception:
            r.drain_ref = None
        r.drain_deadline = (time.monotonic()
                            + self.config.graceful_shutdown_timeout_s + 1.0)

    def _drop(self, r: _Replica, reason: str = "death"):
        """Immediate removal (failed init / failed health check)."""
        _events.record("REPLICA_DIED", deployment=self.dep_id,
                       replica_id=r.replica_id, source=reason)
        _tm.counter_inc("ray_tpu_serve_replica_restarts_total",
                        tags={"deployment": self.dep_id, "reason": reason})
        self._kill(r)

    def _kill(self, r: _Replica):
        import ray_tpu

        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass
        if r in self.replicas:
            self.replicas.remove(r)

    # ------------------------------------------------------------ broadcast
    def broadcast(self):
        entries = [{"replica_id": r.replica_id, "actor_name": r.actor_name,
                    "actor_id": r.actor_id_hex}
                   for r in self.replicas if r.state == RUNNING]
        self.host.notify_changed(
            replicas_key(self.dep_id),
            {"replicas": entries,
             "max_ongoing_requests": self.config.max_ongoing_requests,
             "max_queued_requests": self.config.max_queued_requests})

    def _set_replica_gauges(self):
        counts = {s: 0 for s in (STARTING, RUNNING, STOPPING)}
        for r in self.replicas:
            counts[r.state] = counts.get(r.state, 0) + 1
        for state, n in counts.items():
            _tm.gauge_set("ray_tpu_serve_replicas_tasks", n,
                          tags={"deployment": self.dep_id,
                                "state": state.lower()})
        _tm.gauge_set("ray_tpu_serve_replicas_tasks",
                      0 if self.deleting else self.target_num,
                      tags={"deployment": self.dep_id, "state": "target"})

    def status(self) -> dict:
        return {
            "name": self.spec["name"],
            "status": ("DELETING" if self.deleting else
                       "HEALTHY" if self._num_running() >= self.target_num
                       else "UPDATING"),
            "target_num_replicas": self.target_num,
            "replica_states": {
                s: sum(1 for r in self.replicas if r.state == s)
                for s in (STARTING, RUNNING, STOPPING)},
        }

    def _num_running(self):
        return sum(1 for r in self.replicas if r.state == RUNNING)


class ServeController:
    """The detached controller actor (reference: controller.py:61)."""

    def __init__(self, http_options: dict | None = None):
        self._host = LongPollHost()
        self._lock = threading.RLock()
        self._deployments: dict[str, _DeploymentState] = {}
        self._apps: dict[str, dict] = {}      # name → {route_prefix, ingress}
        self._http_options = http_options or {}
        self._shutdown = threading.Event()
        self._death_watch = self._start_death_watch()
        self._loop = threading.Thread(target=self._run_control_loop,
                                      daemon=True, name="serve-controller")
        self._loop.start()

    def _start_death_watch(self):
        """GCS actor-death subscription: replica death reaches the FSM in
        the feed's publish latency, not a health-check period. Best-effort
        (None without a worker runtime — the health checks still catch
        everything, just slower)."""
        try:
            from ray_tpu._private.pubsub import watch_actor_deaths

            return watch_actor_deaths(self._on_actor_death)
        except Exception:
            return None

    def _on_actor_death(self, actor_id, reason: str):
        hex_id = actor_id.hex() if isinstance(actor_id, bytes) else actor_id
        with self._lock:
            for ds in self._deployments.values():
                if ds.on_actor_death(hex_id):
                    return

    # ------------------------------------------------------------- RPC API
    def listen_for_change(self, snapshot_ids: dict):
        return self._host.listen_for_change(snapshot_ids)

    def get_http_options(self) -> dict:
        return self._http_options

    def deploy_application(self, app_spec: dict):
        """app_spec: {name, route_prefix, ingress, deployments: [dep specs]}
        Each dep spec: {name, user_callable, init_args, init_kwargs, config,
        version}."""
        with self._lock:
            name = app_spec["name"]
            new_deps = {}
            for dep in app_spec["deployments"]:
                dep_id = make_dep_id(name, dep["name"])
                new_deps[dep_id] = dep
            # remove deployments dropped from the app
            old = self._apps.get(name)
            if old:
                for dep_id in old["deployment_ids"]:
                    if dep_id not in new_deps:
                        ds = self._deployments.get(dep_id)
                        if ds:
                            ds.mark_deleting()
            for dep_id, dep in new_deps.items():
                if dep_id in self._deployments and \
                        not self._deployments[dep_id].deleting:
                    self._deployments[dep_id].update_spec(dep)
                else:
                    self._deployments[dep_id] = _DeploymentState(
                        dep_id, dep, self._host)
                self._deployments[dep_id].broadcast()
            self._apps[name] = {
                "route_prefix": app_spec.get("route_prefix"),
                "ingress": make_dep_id(name, app_spec["ingress"]),
                "deployment_ids": list(new_deps),
            }
            self._broadcast_routes()
        return True

    def delete_application(self, name: str):
        with self._lock:
            app = self._apps.pop(name, None)
            if not app:
                return False
            for dep_id in app["deployment_ids"]:
                ds = self._deployments.get(dep_id)
                if ds:
                    ds.mark_deleting()
            self._broadcast_routes()
        return True

    def get_app_status(self, name: str | None = None) -> dict:
        with self._lock:
            out = {}
            for app_name, app in self._apps.items():
                if name is not None and app_name != name:
                    continue
                deps = {}
                for dep_id in app["deployment_ids"]:
                    ds = self._deployments.get(dep_id)
                    if ds:
                        deps[ds.spec["name"]] = ds.status()
                states = [d["status"] for d in deps.values()]
                out[app_name] = {
                    "route_prefix": app["route_prefix"],
                    "ingress": app["ingress"],
                    "status": ("RUNNING" if states and
                               all(s == "HEALTHY" for s in states)
                               else "DEPLOYING"),
                    "deployments": deps,
                }
            return out

    def record_handle_metrics(self, dep_id: str, router_id: str,
                              num_requests: float):
        """Routers push (queued + in-flight) demand for autoscaling."""
        with self._lock:
            ds = self._deployments.get(dep_id)
            if ds is not None:
                ds.handle_metrics[router_id] = (num_requests,
                                                time.monotonic())
        return True

    def get_deployment_info(self, dep_id: str) -> dict | None:
        with self._lock:
            ds = self._deployments.get(dep_id)
            if ds is None:
                return None
            return {"max_ongoing_requests":
                        ds.config.max_ongoing_requests,
                    "max_queued_requests":
                        ds.config.max_queued_requests,
                    "status": ds.status()}

    def graceful_shutdown(self):
        with self._lock:
            for name in list(self._apps):
                self.delete_application(name)
        # wait for replicas to drain out via the control loop
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with self._lock:
                if not self._deployments:
                    break
            time.sleep(0.05)
        self._shutdown.set()
        watch, self._death_watch = self._death_watch, None
        if watch is not None:
            try:
                watch.stop()
            except Exception:
                pass
        return True

    # ------------------------------------------------------------ internals
    def _broadcast_routes(self):
        routes = {}
        for app_name, app in self._apps.items():
            if app.get("route_prefix"):
                routes[app["route_prefix"]] = {
                    "app_name": app_name,
                    "ingress_deployment": app["ingress"],
                }
        self._host.notify_changed(ROUTE_TABLE_KEY, routes)

    def _run_control_loop(self):
        while not self._shutdown.is_set():
            try:
                with self._lock:
                    for dep_id, ds in list(self._deployments.items()):
                        finished = ds.reconcile()
                        if finished:
                            del self._deployments[dep_id]
                            self._host.drop_key(replicas_key(dep_id))
            except Exception:
                import traceback

                traceback.print_exc()
            self._shutdown.wait(RECONCILE_PERIOD_S)

    def ready(self):
        return True
