"""Serve-internal constants (reference: serve/_private/constants.py)."""

SERVE_NAMESPACE = "serve"
CONTROLLER_NAME = "SERVE_CONTROLLER_ACTOR"
PROXY_NAME_PREFIX = "SERVE_PROXY_ACTOR"
DEFAULT_APP_NAME = "default"

# Long-poll keys
ROUTE_TABLE_KEY = "route_table"


def replicas_key(deployment_id: str) -> str:
    return f"replicas::{deployment_id}"


def deployment_id(app_name: str, deployment_name: str) -> str:
    return f"{app_name}#{deployment_name}"
