"""Serve-internal constants (reference: serve/_private/constants.py)."""

SERVE_NAMESPACE = "serve"
CONTROLLER_NAME = "SERVE_CONTROLLER_ACTOR"
PROXY_NAME_PREFIX = "SERVE_PROXY_ACTOR"
DEFAULT_APP_NAME = "default"

# Long-poll keys
ROUTE_TABLE_KEY = "route_table"


def stream_chunk_timeout_s() -> float:
    """Max wait for one streamed chunk (one generator step). Generous by
    default: the FIRST next() of a TPU serving generator may trigger XLA
    compilation (tens of seconds); killing the stream for that would
    truncate a healthy response."""
    from ray_tpu._private.config import get_config

    return float(get_config("serve_stream_chunk_timeout_s"))


def replicas_key(deployment_id: str) -> str:
    return f"replicas::{deployment_id}"


def dep_tag(deployment_id: str) -> str:
    """Fault-plane tag for one deployment's replicas ('#'/':'/'.' are
    schedule-grammar characters, hence the sanitization). The slot
    variant (``slot_tag``) additionally names one replica position —
    it doubles as the name of that slot's capacity placement group
    when the app is a job-plane tenant, so a slot-scoped
    ``preempt_job`` chaos rule and the controller's own drain requests
    address the same gang."""
    return "serve-" + "".join(c if c.isalnum() or c in "-_" else "-"
                              for c in deployment_id)


def slot_tag(deployment_id: str, slot: int) -> str:
    return f"{dep_tag(deployment_id)}-slot{slot}"


def deployment_id(app_name: str, deployment_name: str) -> str:
    return f"{app_name}#{deployment_name}"
