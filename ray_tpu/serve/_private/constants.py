"""Serve-internal constants (reference: serve/_private/constants.py)."""

SERVE_NAMESPACE = "serve"
CONTROLLER_NAME = "SERVE_CONTROLLER_ACTOR"
PROXY_NAME_PREFIX = "SERVE_PROXY_ACTOR"
DEFAULT_APP_NAME = "default"

# Long-poll keys
ROUTE_TABLE_KEY = "route_table"


def stream_chunk_timeout_s() -> float:
    """Max wait for one streamed chunk (one generator step). Generous by
    default: the FIRST next() of a TPU serving generator may trigger XLA
    compilation (tens of seconds); killing the stream for that would
    truncate a healthy response."""
    from ray_tpu._private.config import get_config

    return float(get_config("serve_stream_chunk_timeout_s"))


def replicas_key(deployment_id: str) -> str:
    return f"replicas::{deployment_id}"


def deployment_id(app_name: str, deployment_name: str) -> str:
    return f"{app_name}#{deployment_name}"
