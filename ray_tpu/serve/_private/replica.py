"""Replica actor: wraps the user's deployment callable.

Reference: python/ray/serve/_private/replica.py (RayServeReplica). The
controller creates one named actor per replica from this class. For TPU
serving the typical user class holds a jitted jax program built in
``__init__`` (weights resident on device); ``handle_request`` then runs the
compiled program — the replica actor pins the model to one device/process
exactly like the reference's GPU replicas.
"""
from __future__ import annotations

import threading
import time
import traceback


class ReplicaActor:
    """The body of every Serve replica actor.

    Instantiated via ActorClass options by the controller; the user class is
    shipped pickled (cloudpickle via the runtime's function table).
    """

    def __init__(self, deployment_id: str, replica_id: str,
                 user_callable, init_args, init_kwargs, user_config=None):
        self._deployment_id = deployment_id
        self._replica_id = replica_id
        self._lock = threading.Lock()
        self._num_ongoing = 0
        self._num_total = 0
        self._shutdown = False
        if isinstance(user_callable, type):
            self._user = user_callable(*init_args, **(init_kwargs or {}))
        else:
            # plain function deployment: calls go straight to it
            self._user = user_callable
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------- requests
    def handle_request(self, method_name: str, args, kwargs):
        """Execute one request against the user callable.

        Composition: upstream DeploymentResponses arrive as ObjectRefs
        nested inside `args`; the runtime only auto-resolves top-level actor
        call args, so resolve them here."""
        import ray_tpu
        from ray_tpu import ObjectRef

        args = tuple(ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                     for a in args)
        kwargs = {k: ray_tpu.get(v) if isinstance(v, ObjectRef) else v
                  for k, v in (kwargs or {}).items()}
        with self._lock:
            if self._shutdown:
                raise RuntimeError(
                    f"replica {self._replica_id} is shutting down")
            self._num_ongoing += 1
            self._num_total += 1
        try:
            target = self._resolve_method(method_name)
            return target(*args, **(kwargs or {}))
        finally:
            with self._lock:
                self._num_ongoing -= 1

    def _resolve_method(self, method_name: str):
        if method_name in (None, "", "__call__"):
            if callable(self._user):
                return self._user
            raise AttributeError(
                f"deployment {self._deployment_id} is not callable; "
                f"specify a method name")
        target = getattr(self._user, method_name, None)
        if target is None or not callable(target):
            raise AttributeError(
                f"deployment {self._deployment_id} has no method "
                f"{method_name!r}")
        return target

    # ------------------------------------------------------------ lifecycle
    def reconfigure(self, user_config):
        """Apply a new user_config without restarting (reference:
        replica.py reconfigure → user class's `reconfigure`)."""
        fn = getattr(self._user, "reconfigure", None)
        if callable(fn):
            fn(user_config)
        return True

    def check_health(self):
        fn = getattr(self._user, "check_health", None)
        if callable(fn):
            fn()
        return True

    def get_metrics(self) -> dict:
        with self._lock:
            return {"replica_id": self._replica_id,
                    "num_ongoing_requests": self._num_ongoing,
                    "num_total_requests": self._num_total}

    def prepare_for_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Drain: refuse new work, wait for in-flight requests to finish.
        Returns True if fully drained."""
        with self._lock:
            self._shutdown = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._num_ongoing == 0:
                    break
            time.sleep(0.02)
        fn = getattr(self._user, "__serve_shutdown__", None)
        if callable(fn):
            try:
                fn()
            except Exception:
                traceback.print_exc()
        with self._lock:
            return self._num_ongoing == 0

    def ready(self) -> bool:
        """Liveness probe used by the controller while STARTING."""
        return True
