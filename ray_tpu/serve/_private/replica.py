"""Replica actor: wraps the user's deployment callable.

Reference: python/ray/serve/_private/replica.py (RayServeReplica). The
controller creates one named actor per replica from this class. For TPU
serving the typical user class holds a jitted jax program built in
``__init__`` (weights resident on device); ``handle_request`` then runs the
compiled program — the replica actor pins the model to one device/process
exactly like the reference's GPU replicas.
"""
from __future__ import annotations

import threading
import time
import traceback


class ReplicaActor:
    """The body of every Serve replica actor.

    Instantiated via ActorClass options by the controller; the user class is
    shipped pickled (cloudpickle via the runtime's function table).
    """

    def __init__(self, deployment_id: str, replica_id: str,
                 user_callable, init_args, init_kwargs, user_config=None,
                 slot: int | None = None):
        self._deployment_id = deployment_id
        self._replica_id = replica_id
        self._lock = threading.Lock()
        self._num_ongoing = 0
        self._num_total = 0
        self._shutdown = False
        # live streaming responses: stream_id -> (iterator, last_pull_ts)
        self._streams: dict[str, tuple] = {}
        # tag this process for the seeded fault plane so chaos schedules
        # can target one deployment's replicas deterministically, e.g.
        # `kill_actor:serve-default-Model.handle_request:#3` (same
        # mechanism as train workers' rank<N> tags; '#'/':'/'.' are
        # schedule-grammar characters, hence the sanitization). The slot
        # ordinal (stable per replica position, reused by replacements)
        # gets its own tag so a schedule can kill a MINORITY of capacity
        # — identical processes share the injector's hash stream, so a
        # deployment-wide rule kills every replica in synchronized waves
        try:
            from ray_tpu._private import fault_injection as _fi
            from ray_tpu.serve._private.constants import dep_tag, slot_tag

            _fi.add_tag(dep_tag(deployment_id))
            if slot is not None:
                _fi.add_tag(slot_tag(deployment_id, slot))
        except Exception:
            pass
        if isinstance(user_callable, type):
            self._user = user_callable(*init_args, **(init_kwargs or {}))
        else:
            # plain function deployment: calls go straight to it
            self._user = user_callable
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------- requests
    def handle_request(self, method_name: str, args, kwargs):
        """Execute one request against the user callable.

        Composition: upstream DeploymentResponses arrive as ObjectRefs
        nested inside `args`; the runtime only auto-resolves top-level actor
        call args, so resolve them here."""
        import ray_tpu
        from ray_tpu import ObjectRef

        def _resolve(v):
            if not isinstance(v, ObjectRef):
                return v
            # bounded: an upstream replica that died mid-compose would
            # otherwise hang this request forever, pinning one of the
            # replica's concurrency slots (raylint RTL102); the budget
            # matches the streaming first-chunk allowance (a compile
            # may be in front of the value)
            from ray_tpu._private.config import get_config

            out = ray_tpu.get(
                v, timeout=float(get_config("serve_stream_chunk_timeout_s")))
            if isinstance(out, dict) and "__serve_stream__" in out:
                # upstream deployment streamed: hand the composing user
                # code a chunk iterator, not the raw relay marker
                from ray_tpu.serve.handle import _StreamChunkIterator

                return _StreamChunkIterator(out)
            return out

        args = tuple(_resolve(a) for a in args)
        kwargs = {k: _resolve(v) for k, v in (kwargs or {}).items()}
        with self._lock:
            if self._shutdown:
                # typed: the handle layer re-dispatches to a survivor, so
                # a request racing the drain broadcast is not lost
                from ray_tpu.exceptions import ReplicaDrainingError

                raise ReplicaDrainingError(self._replica_id)
            self._num_ongoing += 1
            self._num_total += 1
        try:
            target = self._resolve_method(method_name)
            result = target(*args, **(kwargs or {}))
            return self._maybe_register_stream(result)
        finally:
            with self._lock:
                self._num_ongoing -= 1

    # ------------------------------------------------------------ streaming
    def _maybe_register_stream(self, result):
        """A generator result (or StreamingResponse wrapping one) stays
        HERE; the caller gets a marker it pulls chunks through
        (stream_next). Reference: http_proxy.py relays starlette
        StreamingResponse bodies; an actor reply is one value, so the
        replica holds the iterator and the proxy long-pulls it."""
        from ray_tpu.serve._private.proxy import StreamingResponse

        status, ctype, headers = 200, "text/plain", {}
        body = result
        if isinstance(result, StreamingResponse):
            status = result.status_code
            ctype = result.content_type
            headers = result.headers
            body = result.body
        if not (hasattr(body, "__next__")
                or (hasattr(body, "__iter__")
                    and isinstance(result, StreamingResponse))):
            return result
        import time as _time
        import uuid as _uuid

        sid = _uuid.uuid4().hex
        with self._lock:
            # lazy sweep: drop streams nothing pulled for 10 minutes
            # (their proxy died mid-stream)
            now = _time.monotonic()
            for k in [k for k, (_, ts) in self._streams.items()
                      if now - ts > 600]:
                self._streams.pop(k, None)
            self._streams[sid] = (iter(body), now)
        return {"__serve_stream__": sid,
                "replica_actor": f"SERVE_REPLICA::{self._replica_id}",
                "status": status, "content_type": ctype,
                "headers": headers}

    def stream_next(self, stream_id: str):
        """Pull the next chunk: ([bytes] or [], done). One chunk per
        call, latency-first: next() on a generator RUNS it to its next
        yield (for token streaming that is a model step), so batching
        ahead would delay the first chunk by the compute of all the
        others. The ~1 ms actor RTT per chunk is the price of
        immediacy; large transfers should yield large chunks."""
        import time as _time

        with self._lock:
            entry = self._streams.get(stream_id)
        if entry is None:
            return [], True
        it = entry[0]
        try:
            chunk = next(it)
        except StopIteration:
            with self._lock:
                self._streams.pop(stream_id, None)
            return [], True
        if isinstance(chunk, str):
            chunk = chunk.encode()
        elif not isinstance(chunk, (bytes, bytearray)):
            chunk = str(chunk).encode()
        with self._lock:
            if stream_id in self._streams:
                self._streams[stream_id] = (it, _time.monotonic())
        return [bytes(chunk)], False

    def stream_cancel(self, stream_id: str):
        with self._lock:
            it = self._streams.pop(stream_id, (None, None))[0]
        close = getattr(it, "close", None)
        if callable(close):
            try:
                close()
            except Exception:
                pass
        return True

    def _resolve_method(self, method_name: str):
        if method_name in (None, "", "__call__"):
            if callable(self._user):
                return self._user
            raise AttributeError(
                f"deployment {self._deployment_id} is not callable; "
                f"specify a method name")
        target = getattr(self._user, method_name, None)
        if target is None or not callable(target):
            raise AttributeError(
                f"deployment {self._deployment_id} has no method "
                f"{method_name!r}")
        return target

    # ------------------------------------------------------------ lifecycle
    def reconfigure(self, user_config):
        """Apply a new user_config without restarting (reference:
        replica.py reconfigure → user class's `reconfigure`)."""
        fn = getattr(self._user, "reconfigure", None)
        if callable(fn):
            fn(user_config)
        return True

    def check_health(self):
        fn = getattr(self._user, "check_health", None)
        if callable(fn):
            fn()
        return True

    def get_metrics(self) -> dict:
        with self._lock:
            # live streams ARE ongoing work: the request isn't done until
            # its generator drains (else the autoscaler downscales a
            # replica mid-token-stream)
            return {"replica_id": self._replica_id,
                    "num_ongoing_requests": (self._num_ongoing
                                             + len(self._streams)),
                    "num_total_requests": self._num_total}

    def prepare_for_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Drain: refuse new work, wait for in-flight requests to finish.
        Returns True if fully drained."""
        with self._lock:
            self._shutdown = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._num_ongoing == 0 and not self._streams:
                    break
            time.sleep(0.02)
        fn = getattr(self._user, "__serve_shutdown__", None)
        if callable(fn):
            try:
                fn()
            except Exception:
                traceback.print_exc()
        with self._lock:
            return self._num_ongoing == 0 and not self._streams

    def ready(self) -> bool:
        """Liveness probe used by the controller while STARTING."""
        return True
