"""Adaptive, shape-aware request batching for Serve deployments.

Reference: python/ray/serve/batching.py (``@serve.batch`` — an asyncio
queue that coalesces concurrent single requests into one call of the
wrapped function on a list). TPU-native motivation is stronger than the
reference's: a jitted forward pass has a fixed per-dispatch cost and the
MXU wants large batch dimensions, so serving throughput hinges on running
one compiled program over many queued requests instead of one program per
request.

**Shape awareness** is the part the reference doesn't need: jit/pjit
compile one program PER INPUT SHAPE, so a naive dynamic batcher that cuts
batches at whatever size the queue happened to hold (3, then 5, then 7,
then 4, ...) recompiles the model once per distinct batch size — exactly
the pjit-cache thrash ``parallel/compile_watch.py`` exists to expose. The
batcher therefore pads every batch up to a small fixed set of bucket
sizes (powers of two up to ``max_batch_size`` by default), so a mixed
traffic stream converges to ZERO recompiles once each bucket has compiled
— at the cost of the padded slots, which are measured
(``ray_tpu_serve_batch_pad_waste_tasks``) rather than hidden. Padding
replicates the last real request, so the wrapped function never sees a
sentinel value; padded outputs are dropped before fan-out. The kill
switch ``RAY_TPU_SERVE_SHAPE_BUCKETS=0`` restores the reference's
pad-free behavior (for CPU-only deployments where recompiles are cheap).

Every batch call is classified against ``compile_watch``'s per-signature
compile cache (``ray_tpu_pjit_cache_total{fn="serve_batch::...", result}``)
— the same instrumentation the training step uses — so "the batcher
stopped recompiling after warmup" is a metric, not a hope. Classification
works at jit's abstraction level: array items classify by (shape, dtype),
so bucketed batches of arrays converge to one signature per bucket.

Replica actors in this runtime execute requests on threads
(``max_concurrency`` > 1, see serve/_private/controller.py), so the
batcher is thread-based: callers enqueue their item and block; a single
lazily-started batcher thread drains the queue into lists bounded by
``max_batch_size``, waiting at most ``batch_wait_timeout_s`` after the
first item arrives, then invokes the wrapped function once per batch and
distributes results back to the callers. On failure each caller gets ITS
OWN clone of the raised exception — a shared exception object mutated by
one caller's handler (``raise ... from``, ``__traceback__`` rewrites)
would corrupt what the other callers observe.
"""
from __future__ import annotations

import copy
import os
import threading
import time
from typing import Callable

from ray_tpu._private import telemetry as _tm


def shape_buckets_enabled() -> bool:
    """Kill switch, read at batcher construction: ``0`` restores the
    legacy pad-free batcher (every queue cut is its own batch size)."""
    return os.environ.get("RAY_TPU_SERVE_SHAPE_BUCKETS", "1") != "0"


def default_bucket_sizes(max_batch_size: int) -> tuple[int, ...]:
    """Powers of two up to (and always including) ``max_batch_size`` —
    log2(max) compiled programs cover every possible batch, and no batch
    pads to more than 2x its real size."""
    sizes, s = [], 1
    while s < max_batch_size:
        sizes.append(s)
        s *= 2
    sizes.append(max_batch_size)
    return tuple(sorted(set(sizes)))


def _clone_exception(exc: BaseException) -> BaseException:
    """A per-caller copy of one batch failure. Clones share the original
    traceback/cause but are DISTINCT objects, so one caller re-raising
    with ``raise e from other`` (which mutates ``__cause__`` and
    ``__context__``) cannot corrupt what the batch's other callers see."""
    try:
        clone = copy.copy(exc)
        if clone is exc or type(clone) is not type(exc):
            return exc
        clone.__traceback__ = exc.__traceback__
        clone.__cause__ = exc.__cause__
        return clone
    except Exception:
        return exc   # unclonable exotic exception: shared beats lost


class _Pending:
    __slots__ = ("item", "event", "result", "error", "trace_ctx",
                 "enq_ns")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error = None
        # captured on the CALLER's thread at enqueue time: the batcher
        # thread that executes the batch has no caller context, so
        # without carrying this the span chain of a traced Serve
        # request breaks at the batching hop
        self.trace_ctx = None
        self.enq_ns = 0


class _Batcher:
    """Queue + single worker thread for one bound batch function."""

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float,
                 bucket_sizes: tuple[int, ...] | None = None,
                 name: str | None = None):
        self._name = name or getattr(fn, "__name__", "batched")
        self._fn = self._instrument(fn)
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        if shape_buckets_enabled():
            self.bucket_sizes = tuple(sorted(
                set(bucket_sizes or default_bucket_sizes(max_batch_size))))
            if self.bucket_sizes[-1] < max_batch_size:
                self.bucket_sizes += (max_batch_size,)
        else:
            self.bucket_sizes = None
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: list[_Pending] = []
        self._thread: threading.Thread | None = None

    def _instrument(self, fn: Callable):
        """Classify every batch call against the pjit-style compile
        cache (parallel/compile_watch.py): array items make the batch
        signature (batch_size, item shape, dtype), so
        ``ray_tpu_pjit_cache_total{fn="serve_batch::<name>"}`` misses
        count exactly the batch shapes the model compiled for — the
        proof metric that bucketing converges to zero recompiles."""
        if not _tm.ENABLED:
            return fn
        try:
            from ray_tpu.parallel.compile_watch import CompiledFunction

            return CompiledFunction(fn, name=f"serve_batch::{self._name}")
        except Exception:
            return fn

    def submit(self, item):
        pending = _Pending(item)
        if _tm.ENABLED:
            try:
                from ray_tpu.util import tracing

                pending.trace_ctx = tracing.inject_context()
                if pending.trace_ctx is not None:
                    pending.enq_ns = time.time_ns()
            except Exception:
                pending.trace_ctx = None
        with self._cond:
            self._queue.append(pending)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="serve-batcher")
                self._thread.start()
            self._cond.notify_all()
        pending.event.wait()
        if pending.error is not None:
            raise pending.error
        return pending.result

    def _take_batch(self) -> list[_Pending] | None:
        """Block for the first item, then linger up to the wait timeout (or
        until the batch fills) before cutting the batch. Returns None when
        idle long enough to let the thread retire."""
        with self._cond:
            deadline = time.monotonic() + 10.0
            while not self._queue:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)
            cutoff = time.monotonic() + self.batch_wait_timeout_s
            while (len(self._queue) < self.max_batch_size):
                remaining = cutoff - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch = self._queue[: self.max_batch_size]
            del self._queue[: len(batch)]
            return batch

    def _pad_to_bucket(self, items: list) -> tuple[list, int]:
        """Pad ``items`` up to the smallest bucket that fits by
        replicating the last real item (never a sentinel — the wrapped
        function must not need a null-request concept). Returns the
        padded list and the pad count; a no-op when bucketing is off."""
        if self.bucket_sizes is None:
            return items, 0
        n = len(items)
        bucket = next(b for b in self.bucket_sizes if b >= n)
        pad = bucket - n
        if pad:
            items = items + [items[-1]] * pad
        return items, pad

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                # Retire quietly; submit() restarts the thread on demand.
                with self._cond:
                    if self._queue:
                        continue
                    self._thread = None
                    return
            items, pad = self._pad_to_bucket([p.item for p in batch])
            exec_start_ns = time.time_ns()
            try:
                results = self._fn(items)
                if results is None or len(results) != len(items):
                    raise TypeError(
                        f"@serve.batch function must return a list with one "
                        f"result per input ({len(items)} expected"
                        f"{f', incl. {pad} padded' if pad else ''}, got "
                        f"{None if results is None else len(results)})")
                for pending, result in zip(batch, results):
                    pending.result = result   # padded tail dropped by zip
                _tm.observe("ray_tpu_serve_batch_size_tasks", len(items),
                            tags={"fn": self._name})
                _tm.observe("ray_tpu_serve_batch_pad_waste_tasks", pad,
                            tags={"fn": self._name})
            except BaseException as exc:  # noqa: BLE001 — fan the error out
                for pending in batch:
                    pending.error = _clone_exception(exc)
            finally:
                self._link_traces(batch, exec_start_ns, len(items), pad)
                for pending in batch:
                    pending.event.set()

    def _link_traces(self, batch, exec_start_ns: int, batch_size: int,
                     pad: int):
        """Re-link each traced caller's span chain across the batching
        hop: one batch-execution span (recorded under the first traced
        item's context), plus one per-item span under the ITEM's own
        caller context covering enqueue → done, carrying the batching
        wait and a ``batch_span`` attribute pointing at the shared
        execution span. A traced Serve request thus shows how long it
        queued and which batch executed it."""
        traced = [p for p in batch if p.trace_ctx is not None]
        if not traced:
            return
        try:
            from ray_tpu.util import tracing

            end_ns = time.time_ns()
            exec_span = tracing.record_completed_span(
                f"serve.batch_execute {self._name}", "INTERNAL",
                exec_start_ns, end_ns,
                attributes={"fn": self._name, "batch_size": batch_size,
                            "pad": pad, "requests": len(batch)},
                ctx=traced[0].trace_ctx)
            batch_span_id = exec_span["span_id"] if exec_span else None
            for p in traced:
                tracing.record_completed_span(
                    f"serve.batch {self._name}", "INTERNAL",
                    p.enq_ns, end_ns,
                    attributes={
                        "fn": self._name,
                        "batch_wait_s":
                            max(0, exec_start_ns - p.enq_ns) / 1e9,
                        "batch_size": batch_size,
                        "batch_span": batch_span_id,
                    },
                    ctx=p.trace_ctx)
        except Exception:
            pass   # tracing must never fail the serving data plane


def _reject_bad_call(args: tuple, kwargs: dict, name: str):
    """One clear error for the two call-shape mistakes, instead of a bare
    arity TypeError from deep inside the batcher."""
    if kwargs:
        raise TypeError(
            f"@serve.batch function {name!r} takes a single positional "
            f"request argument; unexpected keyword arguments "
            f"{sorted(kwargs)} — pack request fields into the one request "
            f"object (the wrapped function receives a LIST of them)")
    if len(args) != 1:
        raise TypeError(
            f"@serve.batch function {name!r} takes exactly one request "
            f"argument per call (got {len(args)}); it is invoked once per "
            f"REQUEST, and the wrapped function receives the batched list")


class _BatchWrapper:
    """The object ``@serve.batch`` produces. Works as a plain function
    wrapper and as a method decorator (descriptor protocol binds one
    batcher per instance, so two replicas in one process never share a
    queue)."""

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float,
                 batch_size_buckets: tuple[int, ...] | None = None):
        if max_batch_size < 1:
            raise ValueError(
                f"max_batch_size must be >= 1, got {max_batch_size}")
        if batch_wait_timeout_s < 0:
            raise ValueError(f"batch_wait_timeout_s must be >= 0, got "
                             f"{batch_wait_timeout_s}")
        if batch_size_buckets:
            bad = [b for b in batch_size_buckets
                   if not isinstance(b, int) or b < 1 or b > max_batch_size]
            if bad:
                # a bucket above max_batch_size would PAD batches past
                # the bound the wrapped function was sized for
                raise ValueError(
                    f"batch_size_buckets must be integers in "
                    f"[1, max_batch_size={max_batch_size}], got {bad}")
        self._fn = fn
        self._max_batch_size = max_batch_size
        self._batch_wait_timeout_s = batch_wait_timeout_s
        self._batch_size_buckets = (tuple(batch_size_buckets)
                                    if batch_size_buckets else None)
        self._batcher: _Batcher | None = None
        # guards batcher creation: the FIRST _make_batcher triggers the
        # (slow) compile_watch import, and concurrent first callers that
        # each saw None would otherwise every one build a private
        # batcher — 8 queues of 1 item each, i.e. no coalescing at all
        self._creation_lock = threading.Lock()
        self._instance_attr = f"__serve_batcher_{id(self)}"
        self.__name__ = getattr(fn, "__name__", "batched")
        self.__doc__ = getattr(fn, "__doc__", None)

    # The wrapper rides inside deployment specs (a class attribute of
    # the user class, cloudpickled to the controller/replicas): ship
    # only the recipe — the creation lock is unpicklable and a live
    # batcher (thread + queue) is meaningless in another process.
    def __getstate__(self):
        return {"fn": self._fn, "max_batch_size": self._max_batch_size,
                "batch_wait_timeout_s": self._batch_wait_timeout_s,
                "batch_size_buckets": self._batch_size_buckets}

    def __setstate__(self, state):
        self.__init__(state["fn"], state["max_batch_size"],
                      state["batch_wait_timeout_s"],
                      state["batch_size_buckets"])

    def _make_batcher(self, fn) -> _Batcher:
        return _Batcher(fn, self._max_batch_size,
                        self._batch_wait_timeout_s,
                        bucket_sizes=self._batch_size_buckets,
                        name=self.__name__)

    def _get_batcher(self, instance=None) -> _Batcher:
        if instance is None:
            if self._batcher is None:
                with self._creation_lock:
                    if self._batcher is None:
                        self._batcher = self._make_batcher(self._fn)
            return self._batcher
        batcher = getattr(instance, self._instance_attr, None)
        if batcher is None:
            with self._creation_lock:
                batcher = getattr(instance, self._instance_attr, None)
                if batcher is None:
                    bound = self._fn.__get__(instance, type(instance))
                    batcher = self._make_batcher(bound)
                    setattr(instance, self._instance_attr, batcher)
        return batcher

    def __call__(self, *args, **kwargs):
        _reject_bad_call(args, kwargs, self.__name__)
        return self._get_batcher().submit(args[0])

    def __get__(self, instance, owner=None):
        if instance is None:
            return self
        batcher = self._get_batcher(instance)
        name = self.__name__

        def bound(*args, **kwargs):
            _reject_bad_call(args, kwargs, name)
            return batcher.submit(args[0])

        bound.__name__ = name
        bound._serve_batcher = batcher
        return bound


def batch(fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01,
          batch_size_buckets: list[int] | tuple[int, ...] | None = None):
    """Coalesce concurrent single-item calls into one list-in/list-out call.

    Usage (method or free function)::

        @serve.deployment(max_ongoing_requests=32)
        class Model:
            @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.005)
            def predict(self, inputs: list):
                return my_jitted_fn(np.stack(inputs)).tolist()

            def __call__(self, x):
                return self.predict(x)

    Each caller passes ONE item and receives ONE result; the wrapped
    function always receives a list and must return an equal-length list.

    Shape awareness: batches are padded up to a small set of bucket sizes
    (powers of two up to ``max_batch_size``, or an explicit
    ``batch_size_buckets``) so a jitted wrapped function compiles a
    handful of programs instead of one per observed batch size. Padded
    slots replicate the last real request and their outputs are dropped.
    ``RAY_TPU_SERVE_SHAPE_BUCKETS=0`` disables padding (legacy behavior).
    """
    if fn is not None:
        return _BatchWrapper(fn, max_batch_size, batch_wait_timeout_s,
                             batch_size_buckets)

    def decorate(inner):
        return _BatchWrapper(inner, max_batch_size, batch_wait_timeout_s,
                             batch_size_buckets)

    return decorate
