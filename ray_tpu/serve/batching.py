"""Adaptive request batching for Serve deployments.

Reference: python/ray/serve/batching.py (``@serve.batch`` — an asyncio
queue that coalesces concurrent single requests into one call of the
wrapped function on a list). TPU-native motivation is stronger than the
reference's: a jitted forward pass has a fixed per-dispatch cost and the
MXU wants large batch dimensions, so serving throughput hinges on running
one compiled program over many queued requests instead of one program per
request.

Replica actors in this runtime execute requests on threads
(``max_concurrency`` > 1, see serve/_private/controller.py), so the
batcher is thread-based: callers enqueue their item and block; a single
lazily-started batcher thread drains the queue into lists bounded by
``max_batch_size``, waiting at most ``batch_wait_timeout_s`` after the
first item arrives, then invokes the wrapped function once per batch and
distributes results (or the raised exception) back to the callers.
"""
from __future__ import annotations

import threading
import time
from typing import Callable


class _Pending:
    __slots__ = ("item", "event", "result", "error")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error = None


class _Batcher:
    """Queue + single worker thread for one bound batch function."""

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self.max_batch_size = max_batch_size
        self.batch_wait_timeout_s = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: list[_Pending] = []
        self._thread: threading.Thread | None = None

    def submit(self, item):
        pending = _Pending(item)
        with self._cond:
            self._queue.append(pending)
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, daemon=True,
                    name="serve-batcher")
                self._thread.start()
            self._cond.notify_all()
        pending.event.wait()
        if pending.error is not None:
            raise pending.error
        return pending.result

    def _take_batch(self) -> list[_Pending] | None:
        """Block for the first item, then linger up to the wait timeout (or
        until the batch fills) before cutting the batch. Returns None when
        idle long enough to let the thread retire."""
        with self._cond:
            deadline = time.monotonic() + 10.0
            while not self._queue:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)
            cutoff = time.monotonic() + self.batch_wait_timeout_s
            while (len(self._queue) < self.max_batch_size):
                remaining = cutoff - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            batch = self._queue[: self.max_batch_size]
            del self._queue[: len(batch)]
            return batch

    def _loop(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                # Retire quietly; submit() restarts the thread on demand.
                with self._cond:
                    if self._queue:
                        continue
                    self._thread = None
                    return
            try:
                results = self._fn([p.item for p in batch])
                if results is None or len(results) != len(batch):
                    raise TypeError(
                        f"@serve.batch function must return a list with one "
                        f"result per input ({len(batch)} expected, got "
                        f"{None if results is None else len(results)})")
                for pending, result in zip(batch, results):
                    pending.result = result
            except BaseException as exc:  # noqa: BLE001 — fan the error out
                for pending in batch:
                    pending.error = exc
            finally:
                for pending in batch:
                    pending.event.set()


class _BatchWrapper:
    """The object ``@serve.batch`` produces. Works as a plain function
    wrapper and as a method decorator (descriptor protocol binds one
    batcher per instance, so two replicas in one process never share a
    queue)."""

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max_batch_size = max_batch_size
        self._batch_wait_timeout_s = batch_wait_timeout_s
        self._batcher: _Batcher | None = None
        self._instance_attr = f"__serve_batcher_{id(self)}"
        self.__name__ = getattr(fn, "__name__", "batched")
        self.__doc__ = getattr(fn, "__doc__", None)

    def _get_batcher(self, instance=None) -> _Batcher:
        if instance is None:
            if self._batcher is None:
                self._batcher = _Batcher(
                    self._fn, self._max_batch_size,
                    self._batch_wait_timeout_s)
            return self._batcher
        batcher = getattr(instance, self._instance_attr, None)
        if batcher is None:
            bound = self._fn.__get__(instance, type(instance))
            batcher = _Batcher(bound, self._max_batch_size,
                               self._batch_wait_timeout_s)
            setattr(instance, self._instance_attr, batcher)
        return batcher

    def __call__(self, *args):
        if len(args) != 1:
            raise TypeError(
                "@serve.batch functions take exactly one request argument "
                f"per call (got {len(args)})")
        return self._get_batcher().submit(args[0])

    def __get__(self, instance, owner=None):
        if instance is None:
            return self
        batcher = self._get_batcher(instance)

        def bound(item):
            return batcher.submit(item)

        bound.__name__ = self.__name__
        bound._serve_batcher = batcher
        return bound


def batch(fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Coalesce concurrent single-item calls into one list-in/list-out call.

    Usage (method or free function)::

        @serve.deployment(max_ongoing_requests=32)
        class Model:
            @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.005)
            def predict(self, inputs: list):
                return my_jitted_fn(np.stack(inputs)).tolist()

            def __call__(self, x):
                return self.predict(x)

    Each caller passes ONE item and receives ONE result; the wrapped
    function always receives a list and must return an equal-length list.
    """
    if fn is not None:
        return _BatchWrapper(fn, max_batch_size, batch_wait_timeout_s)

    def decorate(inner):
        return _BatchWrapper(inner, max_batch_size, batch_wait_timeout_s)

    return decorate
