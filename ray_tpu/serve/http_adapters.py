"""ASGI integration: mount any ASGI app (FastAPI, Starlette, raw ASGI
callables) as a Serve deployment.

Reference: python/ray/serve/_private/http_proxy.py:10-12 (uvicorn
fronting starlette) + serve/api.py `@serve.ingress(app)` (FastAPI apps
mounted into a deployment class). The proxy here is stdlib, so instead
of running uvicorn we drive the ASGI protocol directly: one event loop
per replica, scope built from the proxy's Request, response events
collected — and when the app streams (`more_body=True`), chunks are
surfaced as a generator, which rides Serve's streaming response path
(replica → proxy chunk pull → HTTP chunked transfer encoding).

FastAPI itself is an optional dependency: anything implementing the
ASGI 3.0 callable signature works, which is what the tests exercise
hermetically.
"""
from __future__ import annotations

import asyncio
import queue
import threading
from urllib.parse import urlencode


class ASGIAppWrapper:
    """Deployment body wrapping an ASGI app. Use via ``serve.ingress``:

        app = FastAPI()
        @serve.deployment
        @serve.ingress(app)
        class Api: ...
    """

    def __init__(self, asgi_app):
        self._app = asgi_app
        # One long-lived loop thread per replica: ASGI apps assume a
        # stable loop (startup/shutdown lifespan, background tasks).
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, daemon=True,
            name="serve-asgi-loop")
        self._loop_thread.start()
        self._lifespan_rx = None     # asyncio.Queue feeding the lifespan
        self._start_lifespan()

    def _start_lifespan(self):
        """Best-effort lifespan protocol. The lifespan task STAYS ALIVE
        for the wrapper's lifetime: FastAPI/Starlette run startup and
        shutdown inside one `async with`, parked awaiting the shutdown
        message — cancelling after startup would run the app's shutdown
        logic immediately (closing startup-created pools/model handles
        before the first request). shutdown() delivers the message."""
        async def _install():
            rx = asyncio.Queue()
            started = asyncio.Event()

            async def receive():
                return await rx.get()

            async def send(event):
                if event["type"].startswith("lifespan.startup"):
                    started.set()

            asyncio.ensure_future(self._app(
                {"type": "lifespan", "asgi": {"version": "3.0"}},
                receive, send))
            await rx.put({"type": "lifespan.startup"})
            try:
                await asyncio.wait_for(started.wait(), timeout=10.0)
            except asyncio.TimeoutError:
                pass
            return rx

        try:
            self._lifespan_rx = asyncio.run_coroutine_threadsafe(
                _install(), self._loop).result(timeout=15.0)
        except Exception:
            self._lifespan_rx = None  # lifespan unsupported — fine

    def __serve_shutdown__(self):
        """Called by the replica's graceful drain: deliver
        lifespan.shutdown so the app's teardown runs exactly once."""
        rx = self._lifespan_rx
        if rx is not None:
            try:
                asyncio.run_coroutine_threadsafe(
                    rx.put({"type": "lifespan.shutdown"}),
                    self._loop).result(timeout=5.0)
            except Exception:
                pass
        self._loop.call_soon_threadsafe(self._loop.stop)

    def __call__(self, request):
        """Serve ingress entry: translate Request → ASGI scope, run the
        app, return either a full Response or a chunk generator."""
        scope = {
            "type": "http",
            "asgi": {"version": "3.0", "spec_version": "2.3"},
            "http_version": "1.1",
            "method": request.method,
            "path": request.path,
            "raw_path": request.path.encode(),
            "root_path": "",
            "query_string": urlencode(request.query_params).encode(),
            "headers": [(k.lower().encode(), str(v).encode())
                        for k, v in request.headers.items()],
            "client": ("127.0.0.1", 0),
            "server": ("127.0.0.1", 80),
        }
        events: queue.Queue = queue.Queue()
        body = request.body or b""

        async def _run():
            rx = [
                {"type": "http.request", "body": body, "more_body": False}]

            async def receive():
                if rx:
                    return rx.pop(0)
                return {"type": "http.disconnect"}

            async def send(event):
                events.put(event)

            try:
                await self._app(scope, receive, send)
            except BaseException as e:  # noqa: BLE001 — surface app crashes
                events.put({"type": "__error__", "error": e})
            finally:
                events.put({"type": "__done__"})

        asyncio.run_coroutine_threadsafe(_run(), self._loop)

        start = None
        first_chunks: list[bytes] = []
        while True:
            ev = events.get(timeout=60.0)
            if ev["type"] == "__error__":
                raise ev["error"]
            if ev["type"] == "__done__":
                return self._full_response(start, first_chunks)
            if ev["type"] == "http.response.start":
                start = ev
            elif ev["type"] == "http.response.body":
                first_chunks.append(ev.get("body", b""))
                if ev.get("more_body"):
                    # streaming app → generator response (rides Serve's
                    # chunked streaming path)
                    return self._stream(start, first_chunks, events)

    @staticmethod
    def _headers(start) -> tuple[int, dict, str]:
        status = (start or {}).get("status", 200)
        headers = {}
        ctype = "application/octet-stream"
        for k, v in (start or {}).get("headers", []):
            name = k.decode().lower()
            if name == "content-type":
                ctype = v.decode()
            elif name != "content-length":   # recomputed by the proxy
                headers[name.title()] = v.decode()
        return status, headers, ctype

    def _full_response(self, start, chunks):
        from ray_tpu.serve._private.proxy import Response

        status, headers, ctype = self._headers(start)
        return Response(b"".join(chunks), status_code=status,
                        content_type=ctype, headers=headers)

    def _stream(self, start, first_chunks, events):
        from ray_tpu.serve._private.proxy import StreamingResponse

        def gen():
            for c in first_chunks:
                if c:
                    yield c
            while True:
                ev = events.get(timeout=60.0)
                if ev["type"] == "__error__":
                    raise ev["error"]
                if ev["type"] == "__done__":
                    return
                if ev["type"] == "http.response.body":
                    c = ev.get("body", b"")
                    if c:
                        yield c
                    if not ev.get("more_body"):
                        return

        status, headers, ctype = self._headers(start)
        return StreamingResponse(gen(), status_code=status,
                                 content_type=ctype, headers=headers)


def ingress(asgi_app):
    """Class decorator mounting an ASGI app on a deployment class
    (reference: serve.ingress). Methods of the decorated class remain
    available for handle calls; HTTP requests go to the ASGI app; the
    replica's graceful drain delivers the app's lifespan.shutdown."""
    def decorator(cls):
        class Ingress(cls):
            def __init__(self, *args, **kwargs):
                super().__init__(*args, **kwargs)
                self.__asgi__ = ASGIAppWrapper(asgi_app)

            def __call__(self, request):
                return self.__asgi__(request)

            def __serve_shutdown__(self):
                parent = getattr(super(), "__serve_shutdown__", None)
                if callable(parent):
                    parent()
                self.__asgi__.__serve_shutdown__()

        Ingress.__name__ = cls.__name__
        Ingress.__qualname__ = cls.__qualname__
        return Ingress

    return decorator
