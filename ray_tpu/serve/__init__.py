"""ray_tpu.serve — online model serving on the TPU-native runtime.

Capability parity with the reference's Serve library
(python/ray/serve/, ~32.7k LoC; see SURVEY.md §2.3): a detached controller
actor reconciling a DeploymentState FSM, named replica actors holding the
user callable (for TPU: a jitted jax program with device-resident weights),
in-flight-capped routing with power-of-two-choices, per-node HTTP proxies,
long-poll config push, replica autoscaling, graceful drain, and
model-composition deployment graphs via ``.bind()`` + handle passing.
"""
from ray_tpu.exceptions import (
    ReplicaDrainingError,
    ServeConfigError,
    ServeOverloadedError,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve._private.weights import (
    release_shared_weights,
    shared_weights,
)
from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_app_handle,
    get_deployment_handle,
    http_port,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse
from ray_tpu.serve._private.proxy import Request, Response, StreamingResponse
from ray_tpu.serve.http_adapters import ingress

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "HTTPOptions",
    "ReplicaDrainingError",
    "Request",
    "Response",
    "ServeConfigError",
    "ServeOverloadedError",
    "StreamingResponse",
    "batch",
    "ingress",
    "delete",
    "release_shared_weights",
    "shared_weights",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "http_port",
    "run",
    "shutdown",
    "start",
    "status",
]
