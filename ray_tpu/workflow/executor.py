"""Workflow executor — durable DAG execution with exact resume.

Reference: python/ray/workflow/workflow_executor.py:32,72 (run_until_complete
over a WorkflowState), workflow_state_from_dag.py (DAG → steps),
workflow_state_from_storage.py (resume). The engine:

1. flattens a ray_tpu.dag bind-tree into steps with DETERMINISTIC ids,
   persisting each step's spec (cloudpickled fn + options + arg tree) before
   anything executes — resume never needs the original driver code;
2. runs ready steps as ray_tpu tasks with bounded parallelism, persisting
   each result before the step is considered done;
3. on resume, loads specs from storage, skips steps whose results exist,
   and re-executes the rest — a kill at ANY point replays to the same
   answer (steps must be deterministic/idempotent, as in the reference);
4. supports continuations: a step returning a DAGNode expands into
   sub-steps namespaced under the parent (reference: workflow.continuation).
"""
from __future__ import annotations

from typing import Any

from ray_tpu.dag import ClassMethodNode, ClassNode, DAGNode, FunctionNode, InputNode
from ray_tpu.workflow.storage import WorkflowStorage

MAX_PARALLEL_STEPS = 16


class _StepRef:
    """Marker inside persisted arg trees: 'this argument is the output of
    step X'."""

    __slots__ = ("step_id",)

    def __init__(self, step_id: str):
        self.step_id = step_id

    def __reduce__(self):
        return (_StepRef, (self.step_id,))


def _flatten_dag(node: DAGNode, prefix: str = "") -> tuple[str, dict]:
    """DAG → {step_id: spec}. Deterministic ids: post-order index + fn name
    so the same DAG built twice yields the same ids (resume correctness).
    Returns (output_step_id, specs)."""
    specs: dict[str, dict] = {}
    seen: dict[int, str] = {}
    counter = [0]

    def visit(n: DAGNode) -> str:
        if id(n) in seen:
            return seen[id(n)]
        if isinstance(n, (ClassNode, ClassMethodNode)):
            raise ValueError(
                "workflows execute task DAGs; actor nodes are not durable "
                "(reference workflows have the same task-only core)")
        if isinstance(n, InputNode):
            raise ValueError(
                "workflow DAGs must be fully bound (no InputNode); bind "
                "concrete arguments instead")

        def convert(v):
            if isinstance(v, DAGNode):
                return _StepRef(visit(v))
            return v

        args = tuple(convert(a) for a in n._bound_args)
        kwargs = {k: convert(v) for k, v in n._bound_kwargs.items()}
        fn = n._remote_fn
        sid = f"{prefix}{counter[0]}_{fn._fn.__name__}"
        counter[0] += 1
        specs[sid] = {
            "step_id": sid,
            "fn": fn._fn,
            "options": {k: v for k, v in fn._options.items()
                        if k != "scheduling_strategy"},
            "args": args,
            "kwargs": kwargs,
        }
        seen[id(n)] = sid
        return sid

    if not isinstance(node, FunctionNode):
        raise TypeError(f"workflow.run expects a bound task DAG "
                        f"(fn.bind(...)), got {type(node)}")
    out = visit(node)
    return out, specs


class WorkflowExecutor:
    def __init__(self, workflow_id: str, storage: WorkflowStorage):
        self.workflow_id = workflow_id
        self.storage = storage

    # ------------------------------------------------------------ authoring
    def stage(self, dag: DAGNode):
        """Persist the full step graph before executing anything."""
        output_step, specs = _flatten_dag(dag)
        for sid, spec in specs.items():
            self.storage.save_step_spec(self.workflow_id, sid, spec)
        self.storage.set_output_step(self.workflow_id, output_step)
        self.storage.set_status(self.workflow_id, "RUNNING")

    # ------------------------------------------------------------ execution
    def run_until_complete(self) -> Any:
        wid = self.workflow_id
        try:
            result = self._drive()
            self.storage.set_status(wid, "SUCCEEDED")
            return result
        except BaseException:
            self.storage.set_status(wid, "FAILED")
            raise

    def _drive(self) -> Any:
        import ray_tpu

        wid = self.workflow_id
        specs = self.storage.load_step_specs(wid)
        output_step = self.storage.get_output_step(wid)
        if output_step is None:
            raise ValueError(f"workflow {wid!r} has no staged steps")

        done: dict[str, Any] = {}
        for sid in list(specs):
            if self.storage.has_step_result(wid, sid):
                done[sid] = self.storage.load_step_result(wid, sid)
        self._retry_pending_acks()

        in_flight: dict = {}          # ObjectRef -> step_id
        while True:
            # continuations may have rewritten the output pointer
            output_step = self.storage.get_output_step(wid)
            if output_step in done:
                return done[output_step]
            # launch every ready step (deps done, not running, not done)
            running = set(in_flight.values())
            for sid, spec in sorted(specs.items()):
                if sid in done or sid in running:
                    continue
                if len(in_flight) >= MAX_PARALLEL_STEPS:
                    break
                deps = self._dep_ids(spec)
                if all(d in done for d in deps):
                    ref = self._submit(spec, done)
                    in_flight[ref] = sid
            if not in_flight:
                raise RuntimeError(
                    f"workflow {wid!r} stalled: no runnable steps "
                    f"({len(done)}/{len(specs)} done) — dependency cycle "
                    f"or missing spec")
            ready, _ = ray_tpu.wait(list(in_flight), num_returns=1,
                                    timeout=1.0)
            for ref in ready:
                sid = in_flight.pop(ref)
                value = ray_tpu.get(ref)   # raises → workflow FAILED
                if isinstance(value, DAGNode):
                    # continuation: expand into namespaced sub-steps; the
                    # parent's "result" becomes the sub-DAG's output
                    sub_out, sub_specs = _flatten_dag(
                        value, prefix=f"{sid}/")
                    for ssid, sspec in sub_specs.items():
                        self.storage.save_step_spec(wid, ssid, sspec)
                        specs[ssid] = sspec
                    # alias: parent step forwards the sub-output
                    alias = {
                        "step_id": sid,
                        "fn": _identity,
                        "options": {"num_cpus": 0, "max_retries": 0},
                        "args": (_StepRef(sub_out),),
                        "kwargs": {},
                    }
                    self.storage.save_step_spec(wid, sid, alias)
                    specs[sid] = alias
                    continue
                from ray_tpu.workflow.event_listener import _EventHolder

                if isinstance(value, _EventHolder):
                    # event step: persist the payload FIRST, then ack so
                    # the provider may delete its copy (the reference's
                    # event_checkpointed contract). The ack-pending
                    # marker is written before the result so a failed
                    # ack is RETRIED on resume (without it the stale
                    # provider copy would re-fire a later wait).
                    self.storage.save_pending_ack(wid, sid, value)
                    self.storage.save_step_result(wid, sid, value.event)
                    try:
                        value.ack()
                        self.storage.clear_pending_ack(wid, sid)
                    except Exception:
                        pass   # retried by _retry_pending_acks on resume
                    done[sid] = value.event
                    continue
                self.storage.save_step_result(wid, sid, value)
                done[sid] = value

    def _retry_pending_acks(self):
        """Re-run event-provider acks that failed after their payload
        was checkpointed (crash or transient provider error)."""
        for sid, holder in self.storage.pending_acks(
                self.workflow_id).items():
            try:
                holder.ack()
                self.storage.clear_pending_ack(self.workflow_id, sid)
            except Exception:
                pass   # provider still unreachable; retried next resume

    @staticmethod
    def _dep_ids(spec: dict) -> list[str]:
        deps = [a.step_id for a in spec["args"]
                if isinstance(a, _StepRef)]
        deps += [v.step_id for v in spec["kwargs"].values()
                 if isinstance(v, _StepRef)]
        return deps

    @staticmethod
    def _submit(spec: dict, done: dict):
        import ray_tpu

        def resolve(v):
            if isinstance(v, _StepRef):
                return done[v.step_id]
            return v

        args = tuple(resolve(a) for a in spec["args"])
        kwargs = {k: resolve(v) for k, v in spec["kwargs"].items()}
        opts = dict(spec.get("options") or {})
        remote_fn = ray_tpu.remote(spec["fn"])
        if opts:
            remote_fn = remote_fn.options(**opts)
        return remote_fn.remote(*args, **kwargs)


def _identity(x):
    return x
