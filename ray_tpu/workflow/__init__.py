"""ray_tpu.workflow — durable DAG execution with exact resume.

Capability parity with the reference's Workflow library
(python/ray/workflow/, ~10.1k LoC; see SURVEY.md §2.3): a bound task DAG
(`fn.bind(...)`, from ray_tpu.dag) is staged to durable storage step by
step, executed as runtime tasks with every result persisted before the step
counts as done, and can be resumed after a driver kill — completed steps
replay from storage, pending ones re-execute, and the final answer is
identical. Steps returning a new DAG expand as continuations
(reference: workflow.continuation).
"""
from __future__ import annotations

import uuid
from typing import Any, Optional

from ray_tpu.workflow.executor import WorkflowExecutor
from ray_tpu.workflow.storage import WorkflowStorage

_storage: Optional[WorkflowStorage] = None


def init(storage_dir: str | None = None):
    """Choose the durable storage root (reference: workflow.init)."""
    global _storage
    _storage = WorkflowStorage(storage_dir)
    return _storage


def _get_storage() -> WorkflowStorage:
    global _storage
    if _storage is None:
        _storage = WorkflowStorage()
    return _storage


def run(dag, *, workflow_id: str | None = None,
        storage_dir: str | None = None) -> Any:
    """Stage + execute a DAG durably; returns the output value.
    (reference: workflow/api.py run)"""
    import ray_tpu

    if not ray_tpu.is_initialized():
        raise RuntimeError("call ray_tpu.init() before workflow.run()")
    storage = WorkflowStorage(storage_dir) if storage_dir else _get_storage()
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:8]}"
    ex = WorkflowExecutor(workflow_id, storage)
    ex.stage(dag)
    return ex.run_until_complete()


def resume(workflow_id: str, *, storage_dir: str | None = None) -> Any:
    """Resume a killed/failed workflow from storage: completed steps load,
    the rest re-execute (reference: workflow/api.py resume)."""
    import ray_tpu

    if not ray_tpu.is_initialized():
        raise RuntimeError("call ray_tpu.init() before workflow.resume()")
    storage = WorkflowStorage(storage_dir) if storage_dir else _get_storage()
    if not storage.exists(workflow_id):
        raise ValueError(f"no workflow {workflow_id!r} in storage")
    ex = WorkflowExecutor(workflow_id, storage)
    storage.set_status(workflow_id, "RUNNING")
    return ex.run_until_complete()


def resume_all(*, storage_dir: str | None = None) -> dict[str, Any]:
    """Resume every workflow not in a terminal SUCCEEDED state."""
    storage = WorkflowStorage(storage_dir) if storage_dir else _get_storage()
    out = {}
    for wid, status in storage.list_workflows():
        if status != "SUCCEEDED":
            out[wid] = resume(wid, storage_dir=storage_dir)
    return out


def get_status(workflow_id: str, *, storage_dir: str | None = None) -> str:
    storage = WorkflowStorage(storage_dir) if storage_dir else _get_storage()
    status = storage.get_status(workflow_id)
    if status is None:
        raise ValueError(f"no workflow {workflow_id!r}")
    return status


def get_output(workflow_id: str, *, storage_dir: str | None = None) -> Any:
    """Output of a SUCCEEDED workflow, loaded from storage (no re-run)."""
    storage = WorkflowStorage(storage_dir) if storage_dir else _get_storage()
    out_step = storage.get_output_step(workflow_id)
    if out_step is None or not storage.has_step_result(workflow_id, out_step):
        raise ValueError(f"workflow {workflow_id!r} has no stored output")
    return storage.load_step_result(workflow_id, out_step)


def list_all(*, storage_dir: str | None = None) -> list[tuple[str, str]]:
    storage = WorkflowStorage(storage_dir) if storage_dir else _get_storage()
    return storage.list_workflows()


def delete(workflow_id: str, *, storage_dir: str | None = None):
    storage = WorkflowStorage(storage_dir) if storage_dir else _get_storage()
    storage.delete_workflow(workflow_id)


def continuation(dag):
    """Mark a DAG returned from a step as the step's continuation. Our
    engine treats any returned DAGNode as a continuation, so this is the
    explicit-intent spelling (reference: workflow.continuation)."""
    return dag


from ray_tpu.workflow.event_listener import (  # noqa: E402
    EventListener,
    FileEventListener,
    HTTPEventListener,
    HTTPEventProvider,
    TimerListener,
    wait_for_event,
)

__all__ = ["EventListener", "FileEventListener", "HTTPEventListener",
           "HTTPEventProvider", "TimerListener", "continuation", "delete",
           "get_output", "get_status", "init", "list_all", "resume",
           "resume_all", "run", "wait_for_event"]
