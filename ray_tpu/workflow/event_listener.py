"""Workflow event providers — external-event wait/trigger steps.

Reference: python/ray/workflow/event_listener.py (EventListener with
poll_for_event + event_checkpointed, TimerListener) and
http_event_provider.py (an HTTP endpoint workflows wait on). The
durability contract matches the reference: the event payload is
persisted as the step's result BEFORE `event_checkpointed` fires, so a
provider may delete its copy on ack — a crash after persist but before
ack re-acks (at-least-once ack, exactly-once delivery to downstream
steps).
"""
from __future__ import annotations

import json
import os
import threading
import time
import urllib.request


class EventListener:
    """Contract for event sources a workflow can wait on."""

    def poll_for_event(self):
        """Block until the event arrives; return its payload."""
        raise NotImplementedError

    def event_checkpointed(self, event) -> None:
        """Called AFTER the payload is durably persisted as the step's
        result — the provider may now delete its copy."""


class TimerListener(EventListener):
    """Fires after a duration (reference: event_listener.py
    TimerListener)."""

    def __init__(self, duration_s: float):
        self.duration_s = float(duration_s)

    def poll_for_event(self):
        time.sleep(self.duration_s)
        return {"fired_after_s": self.duration_s}


class FileEventListener(EventListener):
    """Fires when a file appears; payload is its JSON (or raw text)
    contents. Ack deletes the file."""

    def __init__(self, path: str, poll_interval_s: float = 0.1):
        self.path = path
        self.poll_interval_s = poll_interval_s

    def poll_for_event(self):
        while not os.path.exists(self.path):
            time.sleep(self.poll_interval_s)
        with open(self.path) as f:
            raw = f.read()
        try:
            return json.loads(raw)
        except ValueError:
            return raw

    def event_checkpointed(self, event) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class HTTPEventProvider:
    """In-process HTTP endpoint external systems POST events to
    (reference: http_event_provider.py, minus the Serve dependency —
    a plain threaded http.server is enough for the contract).

    POST /event/<key>      body = JSON payload  -> 200
    GET  /event/<key>      -> 200 payload | 404 (listener poll)
    DELETE /event/<key>    -> 200 (listener ack after checkpoint)
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        events: dict[str, bytes] = {}
        lock = threading.Lock()
        self._events = events

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # noqa: N802 — stdlib name
                pass

            def _key(self):
                return self.path.split("/event/", 1)[-1]

            def do_POST(self):   # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                with lock:
                    events[self._key()] = self.rfile.read(n)
                self.send_response(200)
                self.end_headers()

            def do_GET(self):    # noqa: N802
                with lock:
                    body = events.get(self._key())
                if body is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.end_headers()
                self.wfile.write(body)

            def do_DELETE(self):  # noqa: N802
                with lock:
                    events.pop(self._key(), None)
                self.send_response(200)
                self.end_headers()

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="workflow-events")
        self._thread.start()

    @property
    def address(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def pending_events(self) -> list[str]:
        return list(self._events)

    def shutdown(self):
        self._server.shutdown()


class HTTPEventListener(EventListener):
    """Waits on one key of an HTTPEventProvider; ack deletes the
    provider's copy (after the payload is checkpointed)."""

    def __init__(self, provider_address: str, key: str,
                 poll_interval_s: float = 0.2):
        self.url = f"{provider_address}/event/{key}"
        self.poll_interval_s = poll_interval_s

    def poll_for_event(self):
        while True:
            try:
                with urllib.request.urlopen(self.url, timeout=5) as resp:
                    raw = resp.read()
                try:
                    return json.loads(raw)
                except ValueError:
                    return raw.decode()
            except urllib.error.HTTPError as e:
                if e.code != 404:
                    raise
            time.sleep(self.poll_interval_s)

    def event_checkpointed(self, event) -> None:
        req = urllib.request.Request(self.url, method="DELETE")
        try:
            urllib.request.urlopen(req, timeout=5).read()
        except Exception:
            pass   # provider gone: its copy dies with it anyway


class _EventHolder:
    """Marker a wait_for_event step returns: tells the executor to
    persist `.event` as the result, THEN ack via event_checkpointed."""

    __slots__ = ("listener_cls", "args", "kwargs", "event")

    def __init__(self, listener_cls, args, kwargs, event):
        self.listener_cls = listener_cls
        self.args = args
        self.kwargs = kwargs
        self.event = event

    def ack(self):
        self.listener_cls(*self.args, **self.kwargs).event_checkpointed(
            self.event)


def _poll_event_step(listener_cls, args, kwargs):
    listener = listener_cls(*args, **kwargs)
    event = listener.poll_for_event()
    return _EventHolder(listener_cls, args, kwargs, event)


def wait_for_event(event_listener_cls, *args, **kwargs):
    """A bindable DAG node that completes when the listener's event
    arrives; its value (the payload) flows to downstream steps
    (reference: workflow/api.py wait_for_event)."""
    import ray_tpu

    if not issubclass(event_listener_cls, EventListener):
        raise TypeError("wait_for_event takes an EventListener subclass")
    step = ray_tpu.remote(_poll_event_step)
    return step.bind(event_listener_cls, args, kwargs)
