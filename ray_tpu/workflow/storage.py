"""Durable workflow storage — filesystem-backed, atomic per-step records.

Reference: python/ray/workflow/workflow_storage.py + storage/ (pluggable
filesystem/S3 backends). One directory per workflow:

    <root>/<workflow_id>/
        status                  RUNNING | SUCCEEDED | FAILED | CANCELED
        steps/<sid>.spec.pkl    cloudpickled step spec (fn, options, arg tree)
        steps/<sid>.result.pkl  pickled result (present ⇔ step completed)
        output                  step id whose result is the workflow output

Every write is tmp+rename so a crash never leaves a half-written record —
that is what makes kill-and-resume exact."""
from __future__ import annotations

import os
import pickle

import cloudpickle


def _atomic_write(path: str, data: bytes):
    # the shared durability idiom (temp + fsync + rename + dir fsync):
    # step records must survive the crash kill-and-resume replays across
    from ray_tpu._private.atomic_write import atomic_write

    os.makedirs(os.path.dirname(path), exist_ok=True)
    atomic_write(path, data, tag="workflow")


class WorkflowStorage:
    def __init__(self, root: str | None = None):
        self.root = root or os.environ.get(
            "RAY_TPU_WORKFLOW_STORAGE",
            os.path.expanduser("~/.ray_tpu/workflows"))
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------ workflows
    def _wf_dir(self, workflow_id: str) -> str:
        if "/" in workflow_id or workflow_id.startswith("."):
            raise ValueError(f"bad workflow id {workflow_id!r}")
        return os.path.join(self.root, workflow_id)

    def list_workflows(self) -> list[tuple[str, str]]:
        out = []
        for name in sorted(os.listdir(self.root)):
            status_file = os.path.join(self.root, name, "status")
            if os.path.exists(status_file):
                with open(status_file) as f:
                    out.append((name, f.read().strip()))
        return out

    def exists(self, workflow_id: str) -> bool:
        return os.path.exists(os.path.join(self._wf_dir(workflow_id),
                                           "status"))

    def delete_workflow(self, workflow_id: str):
        import shutil

        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)

    def set_status(self, workflow_id: str, status: str):
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "status"),
                      status.encode())

    def get_status(self, workflow_id: str) -> str | None:
        try:
            with open(os.path.join(self._wf_dir(workflow_id), "status")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return None

    def set_output_step(self, workflow_id: str, step_id: str):
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "output"),
                      step_id.encode())

    def get_output_step(self, workflow_id: str) -> str | None:
        try:
            with open(os.path.join(self._wf_dir(workflow_id), "output")) as f:
                return f.read().strip()
        except FileNotFoundError:
            return None

    # ----------------------------------------------------------------- steps
    def _step_path(self, workflow_id: str, step_id: str, kind: str) -> str:
        safe = step_id.replace("/", "__")
        return os.path.join(self._wf_dir(workflow_id), "steps",
                            f"{safe}.{kind}.pkl")

    def save_step_spec(self, workflow_id: str, step_id: str, spec: dict):
        _atomic_write(self._step_path(workflow_id, step_id, "spec"),
                      cloudpickle.dumps(spec))

    def load_step_specs(self, workflow_id: str) -> dict[str, dict]:
        steps_dir = os.path.join(self._wf_dir(workflow_id), "steps")
        specs = {}
        if not os.path.isdir(steps_dir):
            return specs
        for name in os.listdir(steps_dir):
            if name.endswith(".spec.pkl") and not name.startswith(".tmp"):
                with open(os.path.join(steps_dir, name), "rb") as f:
                    spec = pickle.load(f)
                specs[spec["step_id"]] = spec
        return specs

    def save_step_result(self, workflow_id: str, step_id: str, value):
        _atomic_write(self._step_path(workflow_id, step_id, "result"),
                      cloudpickle.dumps(value))

    def has_step_result(self, workflow_id: str, step_id: str) -> bool:
        return os.path.exists(self._step_path(workflow_id, step_id,
                                              "result"))

    def load_step_result(self, workflow_id: str, step_id: str):
        with open(self._step_path(workflow_id, step_id, "result"),
                  "rb") as f:
            return pickle.load(f)

    # pending event-provider acks (executor retries them on resume)
    def save_pending_ack(self, workflow_id: str, step_id: str, holder):
        _atomic_write(self._step_path(workflow_id, step_id, "ack"),
                      cloudpickle.dumps(holder))

    def pending_acks(self, workflow_id: str) -> dict[str, object]:
        steps_dir = os.path.join(self._wf_dir(workflow_id), "steps")
        out = {}
        if not os.path.isdir(steps_dir):
            return out
        for name in os.listdir(steps_dir):
            if name.endswith(".ack.pkl") and not name.startswith(".tmp"):
                sid = name[:-len(".ack.pkl")].replace("__", "/")
                with open(os.path.join(steps_dir, name), "rb") as f:
                    out[sid] = pickle.load(f)
        return out

    def clear_pending_ack(self, workflow_id: str, step_id: str):
        try:
            os.unlink(self._step_path(workflow_id, step_id, "ack"))
        except OSError:
            pass
