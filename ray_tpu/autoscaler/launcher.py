"""Cluster launcher — `ray-tpu up/down <cluster.yaml>`.

Reference: python/ray/scripts/scripts.py:1164 (`ray up`) / :1240
(`ray down`) + autoscaler/_private/commands.py (create_or_update_cluster,
teardown_cluster). The YAML schema keeps the reference's field names
(cluster_name, max_workers, provider, available_node_types,
head_node_type — see autoscaler/gcp/tpu.yaml:29) with a TPU-first
provider set:

    cluster_name: demo
    max_workers: 4
    idle_timeout_s: 60
    provider:
      type: mock            # local | mock | gce_tpu
      # gce_tpu: project, zone, runtime_version
    head_node_type: head
    available_node_types:
      head:
        resources: {CPU: 2}
      v5e_pod:
        min_workers: 0
        max_workers: 4
        resources: {CPU: 4, TPU: 4}
        tpu_slice: {accelerator_type: v5litepod-16, topology: 4x4,
                    hosts: 4}

`up` starts a head node process, records cluster state under
/tmp/ray_tpu/clusters/<name>.json, and spawns a detached monitor
process (`python -m ray_tpu.autoscaler.monitor`) that owns the provider
and runs the StandardAutoscaler reconcile loop — the reference's
monitor.py shape. `down` signals the monitor (which releases every
provider node/slice on SIGTERM), then stops the head.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

STATE_DIR = "/tmp/ray_tpu/clusters"


def load_cluster_config(path: str) -> dict:
    import yaml

    with open(path) as f:
        cfg = yaml.safe_load(f)
    for key in ("cluster_name", "provider", "available_node_types"):
        if key not in cfg:
            raise ValueError(f"cluster config missing required key {key!r}")
    head_type = cfg.get("head_node_type")
    if head_type and head_type not in cfg["available_node_types"]:
        raise ValueError(f"head_node_type {head_type!r} not in "
                         f"available_node_types")
    return cfg


def make_provider(cfg: dict, gcs_address: str):
    """Provider registry (reference: autoscaler/_private/providers.py
    _get_node_provider). Worker-node providers attach to the running
    cluster's GCS so scaled nodes join it."""
    ptype = cfg["provider"].get("type", "local")
    cluster = cfg.get("cluster_name", "ray-tpu")
    if ptype == "local":
        from ray_tpu.autoscaler.node_provider import LocalNodeProvider

        return LocalNodeProvider(gcs_address)
    if ptype == "mock":
        from ray_tpu.autoscaler.tpu_provider import (MockTpuApi,
                                                     TPUPodNodeProvider)

        p = cfg["provider"]
        api = MockTpuApi(gcs_address,
                         provision_delay_s=p.get("provision_delay_s", 0.0),
                         capacity_hosts=p.get("capacity_hosts"))
        return TPUPodNodeProvider(api, cluster)
    if ptype == "gce_tpu":
        from ray_tpu.autoscaler.tpu_provider import (GceTpuApi,
                                                     TPUPodNodeProvider)

        p = cfg["provider"]
        api = GceTpuApi(p["project"], p["zone"],
                        p.get("runtime_version", "v2-alpha-tpuv5-lite"))
        return TPUPodNodeProvider(api, cluster)
    raise ValueError(f"unknown provider type {ptype!r}")


def _state_path(cluster_name: str) -> str:
    return os.path.join(STATE_DIR, f"{cluster_name}.json")


def up(config_path: str, *, no_monitor: bool = False) -> dict:
    """Create (or reconnect to) the cluster described by the YAML.
    Returns the cluster state dict {gcs_address, head_pid, monitor_pid}."""
    cfg = load_cluster_config(config_path)
    name = cfg["cluster_name"]
    os.makedirs(STATE_DIR, exist_ok=True)
    state_file = _state_path(name)
    if os.path.exists(state_file):
        with open(state_file) as f:
            state = json.load(f)
        if _alive(state.get("head_pid")):
            # idempotent re-up — but a dead monitor means nobody owns the
            # provider's nodes/slices (its SIGTERM handler is what
            # releases them on `down`): respawn it
            if not no_monitor and not _alive(state.get("monitor_pid")):
                mon = subprocess.Popen(
                    [sys.executable, "-m", "ray_tpu.autoscaler.monitor",
                     "--config", os.path.abspath(config_path),
                     "--gcs-address", state["gcs_address"]],
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    start_new_session=True)
                state["monitor_pid"] = mon.pid
                with open(state_file, "w") as f:
                    json.dump(state, f)
            return state
        os.unlink(state_file)

    head_type = cfg.get("head_node_type")
    head_spec = (cfg["available_node_types"].get(head_type, {})
                 if head_type else {})
    head_res = dict(head_spec.get("resources") or {"CPU": 1})
    node_args = [sys.executable, "-m", "ray_tpu.scripts.node", "--head",
                 "--num-cpus", str(int(head_res.get("CPU", 1))),
                 "--object-store-memory",
                 str(head_spec.get("object_store_memory",
                                   128 * 1024 * 1024))]
    extra = {k: v for k, v in head_res.items()
             if k not in ("CPU", "memory")}
    if extra:
        node_args += ["--resources", json.dumps(extra)]
    ready = os.path.join(STATE_DIR, f"ready_{name}_{time.time_ns()}")
    node_args += ["--ready-file", ready]
    head = subprocess.Popen(node_args, stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)
    deadline = time.time() + 90
    info = None
    while time.time() < deadline:
        if os.path.exists(ready):
            with open(ready) as f:
                info = json.load(f)
            os.unlink(ready)
            break
        if head.poll() is not None:
            raise RuntimeError("head node died during ray-tpu up")
        time.sleep(0.1)
    if info is None:
        head.kill()
        raise TimeoutError("head node not ready in 90s")

    monitor_pid = None
    if not no_monitor:
        mon = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.autoscaler.monitor",
             "--config", os.path.abspath(config_path),
             "--gcs-address", info["gcs_address"]],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            start_new_session=True)
        monitor_pid = mon.pid

    state = {"cluster_name": name, "config_path": os.path.abspath(
        config_path), "gcs_address": info["gcs_address"],
        "head_pid": head.pid, "monitor_pid": monitor_pid,
        "started_at": time.time()}
    with open(state_file, "w") as f:
        json.dump(state, f)
    return state


def down(config_path_or_name: str, *, timeout: float = 30.0) -> bool:
    """Tear the cluster down: the monitor releases every provider
    node/slice on SIGTERM, then the head is stopped. Returns True if a
    running cluster was found."""
    name = config_path_or_name
    if os.path.exists(config_path_or_name):
        name = load_cluster_config(config_path_or_name)["cluster_name"]
    state_file = _state_path(name)
    if not os.path.exists(state_file):
        return False
    with open(state_file) as f:
        state = json.load(f)

    mon_pid = state.get("monitor_pid")
    if mon_pid and _alive(mon_pid):
        os.kill(mon_pid, signal.SIGTERM)
        deadline = time.time() + timeout
        while _alive(mon_pid) and time.time() < deadline:
            time.sleep(0.1)
        if _alive(mon_pid):
            os.kill(mon_pid, signal.SIGKILL)

    head_pid = state.get("head_pid")
    if head_pid and _alive(head_pid):
        os.kill(head_pid, signal.SIGTERM)
        deadline = time.time() + timeout
        while _alive(head_pid) and time.time() < deadline:
            time.sleep(0.1)
        if _alive(head_pid):
            os.kill(head_pid, signal.SIGKILL)
    os.unlink(state_file)
    return True


def _alive(pid) -> bool:
    if not pid:
        return False
    try:
        # reap if it's our child (up() in-process): a zombie passes the
        # kill-0 probe forever otherwise
        os.waitpid(pid, os.WNOHANG)
    except OSError:
        pass
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            if f.read().rsplit(")", 1)[1].split()[0] == "Z":
                return False
    except OSError:
        return False
    return True
