"""StandardAutoscaler — the update loop.

Reference: python/ray/autoscaler/_private/autoscaler.py:162 (update at
:353) + resource_demand_scheduler.py:103,171 (binpack demand onto node
types). Each update():

1. pulls cluster load from the GCS (queued request shapes + pending PG
   bundles + per-node availability),
2. binpacks unfulfilled demand onto current headroom; what doesn't fit is
   matched against available_node_types (first type whose resources cover
   the shape, respecting per-type and global max_workers) → create_node,
3. terminates provider nodes idle past idle_timeout_s (no leases/actors,
   no queued demand), never dropping below min_workers.

Run it via a thread (`start()`) or drive `update()` manually (tests, and
the reference's monitor.py does the same single-threaded loop).
"""
from __future__ import annotations

import threading
import time


class StandardAutoscaler:
    def __init__(self, gcs_address: str, config: dict, provider):
        """config: {
            "max_workers": int, "min_workers": int (default 0),
            "idle_timeout_s": float,
            "available_node_types": {name: {"resources": {...},
                                            "max_workers": int}},
        }"""
        from ray_tpu._private.protocol import RpcClient

        host, port = gcs_address.rsplit(":", 1)
        self._gcs = RpcClient((host, int(port)), timeout=10.0)
        self.config = config
        self.provider = provider
        self._idle_since: dict[str, float] = {}   # provider_id -> ts
        self._stop = threading.Event()
        self._thread = None

    # ---------------------------------------------------------------- loop
    def start(self, interval_s: float = 5.0):
        self._thread = threading.Thread(
            target=self._loop, args=(interval_s,), daemon=True,
            name="autoscaler")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._gcs.close()
        except Exception:
            pass

    def _loop(self, interval_s: float):
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:
                pass
            self._stop.wait(interval_s)

    # -------------------------------------------------------------- update
    def update(self) -> dict:
        """One reconcile pass. Returns {"launched": [...], "terminated":
        [...]} for observability/tests."""
        from ray_tpu.autoscaler.resource_demand import get_nodes_to_launch

        load = self._gcs.call("get_cluster_load")
        alive = [n for n in load["nodes"] if n["Alive"]]
        task_demand = [d for n in alive for d in n["PendingDemand"]]
        # strategy-aware PG demand when the GCS provides it; flat bundles
        # (no co-location/anti-affinity constraints) otherwise
        pending_pgs = load.get("pending_pgs")
        if pending_pgs is None:
            pending_pgs = [{"strategy": "PACK",
                            "bundles": load["pending_pg_bundles"]}]

        types = self.config.get("available_node_types", {})
        provider_nodes = self.provider.non_terminated_nodes()
        by_type: dict[str, int] = {}
        for n in provider_nodes:
            by_type[n["node_type"]] = by_type.get(n["node_type"], 0) + 1

        plan, infeasible = get_nodes_to_launch(
            task_demand, pending_pgs,
            headroom=[dict(n["Available"]) for n in alive],
            node_types=types,
            counts_by_type=by_type,
            max_workers=self.config.get("max_workers", 8))

        launched = []
        for name, count in plan.items():
            spec = types[name]
            slice_cfg = spec.get("tpu_slice")
            if slice_cfg:
                # multi-host TPU slices launch as a UNIT (QR-style "give
                # me a slice of topology X"); provider decides how
                for _ in range(count):
                    launched.extend(self.provider.create_slice(
                        name, spec, slice_cfg.get("topology", "")))
            else:
                launched.extend(self.provider.create_node(name, spec,
                                                          count))

        terminated = []
        if not plan and not infeasible:
            terminated = self._scale_down(alive)
        return {"launched": launched, "terminated": terminated,
                "unfulfilled": infeasible}

    def _scale_down(self, alive_nodes: list[dict]) -> list[str]:
        idle_timeout = self.config.get("idle_timeout_s", 60.0)
        min_workers = self.config.get("min_workers", 0)
        by_runtime_id = {n["NodeID"]: n for n in alive_nodes}
        provider_nodes = self.provider.non_terminated_nodes()
        now = time.time()
        terminated = []
        for pn in provider_nodes:
            n = by_runtime_id.get(pn.get("node_id"))
            busy = n is None or n["Busy"] > 0 or n["PendingDemand"]
            if busy:
                self._idle_since.pop(pn["provider_id"], None)
                continue
            first_idle = self._idle_since.setdefault(pn["provider_id"], now)
            if now - first_idle < idle_timeout:
                continue
            if len(provider_nodes) - len(terminated) <= min_workers:
                break
            self.provider.terminate_node(pn["provider_id"])
            self._idle_since.pop(pn["provider_id"], None)
            terminated.append(pn["provider_id"])
        return terminated
