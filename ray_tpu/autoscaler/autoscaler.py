"""StandardAutoscaler — the update loop.

Reference: python/ray/autoscaler/_private/autoscaler.py:162 (update at
:353) + resource_demand_scheduler.py:103,171 (binpack demand onto node
types). Each update():

1. pulls cluster load from the GCS (queued request shapes + pending PG
   bundles + per-node availability),
2. binpacks unfulfilled demand onto current headroom; what doesn't fit is
   matched against available_node_types (first type whose resources cover
   the shape, respecting per-type and global max_workers) → create_node,
3. terminates provider nodes idle past idle_timeout_s (no leases/actors,
   no queued demand), never dropping below min_workers.

Run it via a thread (`start()`) or drive `update()` manually (tests, and
the reference's monitor.py does the same single-threaded loop).
"""
from __future__ import annotations

import threading
import time


class StandardAutoscaler:
    def __init__(self, gcs_address: str, config: dict, provider):
        """config: {
            "max_workers": int, "min_workers": int (default 0),
            "idle_timeout_s": float,
            "available_node_types": {name: {"resources": {...},
                                            "max_workers": int}},
        }"""
        from ray_tpu._private.protocol import RpcClient

        host, port = gcs_address.rsplit(":", 1)
        self._gcs = RpcClient((host, int(port)), timeout=10.0)
        self.config = config
        self.provider = provider
        self._idle_since: dict[str, float] = {}   # provider_id -> ts
        self._stop = threading.Event()
        self._thread = None

    # ---------------------------------------------------------------- loop
    def start(self, interval_s: float = 5.0):
        self._thread = threading.Thread(
            target=self._loop, args=(interval_s,), daemon=True,
            name="autoscaler")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._gcs.close()
        except Exception:
            pass

    def _loop(self, interval_s: float):
        while not self._stop.is_set():
            try:
                self.update()
            except Exception:
                pass
            self._stop.wait(interval_s)

    # -------------------------------------------------------------- update
    def update(self) -> dict:
        """One reconcile pass. Returns {"launched": [...], "terminated":
        [...]} for observability/tests."""
        load = self._gcs.call("get_cluster_load")
        alive = [n for n in load["nodes"] if n["Alive"]]
        demand = [d for n in alive for d in n["PendingDemand"]]
        demand += load["pending_pg_bundles"]

        # 1. subtract what current headroom can absorb
        headroom = [dict(n["Available"]) for n in alive]
        unfulfilled = []
        for shape in demand:
            placed = False
            for h in headroom:
                if all(h.get(k, 0) >= v for k, v in shape.items()):
                    for k, v in shape.items():
                        h[k] = h.get(k, 0) - v
                    placed = True
                    break
            if not placed:
                unfulfilled.append(shape)

        launched = []
        if unfulfilled:
            launched = self._launch_for(unfulfilled)

        terminated = []
        if not unfulfilled:
            terminated = self._scale_down(alive)
        return {"launched": launched, "terminated": terminated,
                "unfulfilled": unfulfilled}

    def _launch_for(self, shapes: list[dict]) -> list[str]:
        types = self.config.get("available_node_types", {})
        provider_nodes = self.provider.non_terminated_nodes()
        total = len(provider_nodes)
        by_type: dict[str, int] = {}
        for n in provider_nodes:
            by_type[n["node_type"]] = by_type.get(n["node_type"], 0) + 1
        launched = []
        # plan: first node type that covers each shape (reference binpacking
        # picks min-cost; first-fit is our simplification), dedup into
        # counts, honor caps
        plan: dict[str, int] = {}
        pending_cover: dict[str, dict] = {}
        for shape in shapes:
            for name, spec in types.items():
                res = spec.get("resources", {})
                if all(res.get(k, 0) >= v for k, v in shape.items()):
                    cover = pending_cover.setdefault(name, dict(res))
                    if all(cover.get(k, 0) >= v for k, v in shape.items()):
                        # fits in a node we already plan to launch
                        for k, v in shape.items():
                            cover[k] = cover.get(k, 0) - v
                        plan.setdefault(name, max(plan.get(name, 0), 1))
                    else:
                        plan[name] = plan.get(name, 0) + 1
                        pending_cover[name] = dict(res)
                        for k, v in shape.items():
                            pending_cover[name][k] = \
                                pending_cover[name].get(k, 0) - v
                    break
        max_workers = self.config.get("max_workers", 8)
        for name, count in plan.items():
            spec = types[name]
            cap = spec.get("max_workers", max_workers)
            allowed = min(count,
                          cap - by_type.get(name, 0),
                          max_workers - total - len(launched))
            if allowed <= 0:
                continue
            launched.extend(self.provider.create_node(name, spec, allowed))
        return launched

    def _scale_down(self, alive_nodes: list[dict]) -> list[str]:
        idle_timeout = self.config.get("idle_timeout_s", 60.0)
        min_workers = self.config.get("min_workers", 0)
        by_runtime_id = {n["NodeID"]: n for n in alive_nodes}
        provider_nodes = self.provider.non_terminated_nodes()
        now = time.time()
        terminated = []
        for pn in provider_nodes:
            n = by_runtime_id.get(pn.get("node_id"))
            busy = n is None or n["Busy"] > 0 or n["PendingDemand"]
            if busy:
                self._idle_since.pop(pn["provider_id"], None)
                continue
            first_idle = self._idle_since.setdefault(pn["provider_id"], now)
            if now - first_idle < idle_timeout:
                continue
            if len(provider_nodes) - len(terminated) <= min_workers:
                break
            self.provider.terminate_node(pn["provider_id"])
            self._idle_since.pop(pn["provider_id"], None)
            terminated.append(pn["provider_id"])
        return terminated
