"""TPU-pod NodeProvider — slice-atomic provisioning via a QR-shaped API.

Reference: python/ray/autoscaler/_private/gcp/node.py (GCPTPUNode +
GCPResource REST abstraction, the `tpu.yaml` node_config shape at
autoscaler/gcp/tpu.yaml:29). The reference provisions TPU VMs one at a
time through the TPU REST API; pods (multi-host slices) need the
queued-resources (QR) API, where a slice of topology X is requested,
granted, and deleted AS A UNIT. This provider is built around that
unit-of-slice contract from the start:

- `TpuApi` is the pluggable transport: `create_slice` asks for a whole
  slice (accelerator type + topology), `delete_slice` releases it,
  `list_slices` reports slice state with per-host VM records.
- `TPUPodNodeProvider` maps the autoscaler's create/terminate calls
  onto slices: terminating ANY host of a slice releases the whole
  slice (you cannot shrink a pod), and a slice only counts once every
  host is RUNNING — partially-provisioned slices are invisible to
  binpacking, matching QR's all-or-nothing grant semantics.
- `MockTpuApi` is the test double (reference analog:
  autoscaler/_private/fake_multi_node/): in-memory slice records, a
  configurable provisioning delay, optional capacity ceiling (QR quota
  exhaustion), and — when given a GCS address — REAL local node
  processes per host so `ray-tpu up` against provider.type "mock"
  yields a working cluster end-to-end.
- `GceTpuApi` shapes the real REST calls (create/get/delete
  queuedResources under a project/zone parent). It builds the exact
  request bodies and URLS; actually issuing them requires credentials
  + network, so each call funnels through `_execute`, which a
  subclass or test can override.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import uuid

from ray_tpu.autoscaler.node_provider import NodeProvider

# Slice states (QR vocabulary: WAITING_FOR_RESOURCES → PROVISIONING →
# ACTIVE → SUSPENDING/SUSPENDED; we keep the ones that matter here)
PROVISIONING = "PROVISIONING"
ACTIVE = "ACTIVE"
DELETING = "DELETING"


class TpuApi:
    """Transport contract for the queued-resources shaped calls."""

    def create_slice(self, name: str, accelerator_type: str,
                     topology: str, hosts: int, node_config: dict) -> str:
        """Request one slice as a unit; returns the slice id. The grant
        is asynchronous: poll list_slices() for state."""
        raise NotImplementedError

    def delete_slice(self, slice_id: str) -> None:
        raise NotImplementedError

    def list_slices(self) -> list[dict]:
        """[{slice_id, name, state, hosts: [{host_id, node_id?}, ...]}]"""
        raise NotImplementedError


class TPUPodNodeProvider(NodeProvider):
    """Autoscaler-facing provider over a TpuApi.

    provider_id format: "<slice_id>/<host_index>" — the autoscaler sees
    hosts (it binpacks per-host resources), but create and terminate
    operate on slices.
    """

    def __init__(self, api: TpuApi, cluster_name: str = "ray-tpu"):
        self.api = api
        self.cluster_name = cluster_name

    # ------------------------------------------------------------- listing
    def non_terminated_nodes(self) -> list[dict]:
        out = []
        for s in self.api.list_slices():
            if s["state"] == DELETING:
                continue
            # A slice is schedulable capacity only when FULLY granted:
            # QR grants are all-or-nothing, and advertising a
            # half-provisioned pod would let the binpacker place gang
            # bundles on hosts that may never arrive.
            if s["state"] != ACTIVE:
                continue
            for i, host in enumerate(s["hosts"]):
                out.append({"provider_id": f"{s['slice_id']}/{i}",
                            "node_type": s.get("node_type", "tpu_pod"),
                            "node_id": host.get("node_id"),
                            "slice_id": s["slice_id"]})
        return out

    def pending_slices(self) -> list[dict]:
        return [s for s in self.api.list_slices()
                if s["state"] == PROVISIONING]

    # ------------------------------------------------------------ creation
    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> list[str]:
        """Single-host creation = a 1-host slice per node (v5e-1 style)."""
        created = []
        for _ in range(count):
            created.extend(self.create_slice(node_type, node_config, ""))
        return created

    def create_slice(self, node_type: str, node_config: dict,
                     topology: str) -> list[str]:
        slice_cfg = node_config.get("tpu_slice") or {}
        hosts = int(slice_cfg.get("hosts", 1))
        accel = slice_cfg.get("accelerator_type",
                              node_config.get("acceleratorType", "v5e-8"))
        topology = topology or slice_cfg.get("topology", "")
        name = f"{self.cluster_name}-{node_type}-{uuid.uuid4().hex[:8]}"
        slice_id = self.api.create_slice(name, accel, topology, hosts,
                                         dict(node_config,
                                              node_type=node_type))
        return [f"{slice_id}/{i}" for i in range(hosts)]

    # --------------------------------------------------------- termination
    def terminate_node(self, provider_id: str) -> None:
        """Slice-atomic: releasing any host releases the slice (pods do
        not shrink). The autoscaler's idle scan asks per-host; the
        second ask for the same slice is a no-op."""
        slice_id = provider_id.split("/", 1)[0]
        self.api.delete_slice(slice_id)

    def shutdown(self):
        for s in self.api.list_slices():
            try:
                self.api.delete_slice(s["slice_id"])
            except Exception:
                pass


class MockTpuApi(TpuApi):
    """In-memory QR double; optionally backs hosts with real local node
    processes so launcher E2E tests exercise the whole path."""

    def __init__(self, gcs_address: str | None = None,
                 provision_delay_s: float = 0.0,
                 capacity_hosts: int | None = None):
        self.gcs_address = gcs_address
        self.provision_delay_s = provision_delay_s
        self.capacity_hosts = capacity_hosts
        self._slices: dict[str, dict] = {}
        self._lock = threading.Lock()
        self.requests: list[dict] = []   # audit trail for tests

    # -- TpuApi ------------------------------------------------------------
    def create_slice(self, name, accelerator_type, topology, hosts,
                     node_config):
        with self._lock:
            in_use = sum(len(s["hosts"]) for s in self._slices.values()
                         if s["state"] != DELETING)
            if self.capacity_hosts is not None and \
                    in_use + hosts > self.capacity_hosts:
                raise RuntimeError(
                    f"QUOTA_EXHAUSTED: {in_use}+{hosts} hosts over "
                    f"capacity {self.capacity_hosts}")
            slice_id = f"qr-{uuid.uuid4().hex[:12]}"
            rec = {"slice_id": slice_id, "name": name,
                   "accelerator_type": accelerator_type,
                   "topology": topology,
                   "node_type": node_config.get("node_type", "tpu_pod"),
                   "state": PROVISIONING,
                   "created_at": time.time(),
                   "node_config": node_config,
                   "hosts": [{"host_id": f"{name}-w{i}"}
                             for i in range(hosts)]}
            self._slices[slice_id] = rec
            self.requests.append({"op": "create", "name": name,
                                  "accelerator_type": accelerator_type,
                                  "topology": topology, "hosts": hosts})
        if self.provision_delay_s:
            threading.Thread(target=self._provision_later,
                             args=(slice_id,), daemon=True).start()
        else:
            self._activate(slice_id)
        return slice_id

    def delete_slice(self, slice_id):
        with self._lock:
            rec = self._slices.get(slice_id)
            if rec is None or rec["state"] == DELETING:
                return
            rec["state"] = DELETING
            self.requests.append({"op": "delete", "slice_id": slice_id})
            procs = [h.pop("proc", None) for h in rec["hosts"]]
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()
        with self._lock:
            self._slices.pop(slice_id, None)

    def list_slices(self):
        with self._lock:
            return [
                {"slice_id": s["slice_id"], "name": s["name"],
                 "state": s["state"], "node_type": s["node_type"],
                 "topology": s["topology"],
                 "hosts": [dict(h) for h in s["hosts"]]}
                for s in self._slices.values()
            ]

    # -- internals ---------------------------------------------------------
    def _provision_later(self, slice_id):
        time.sleep(self.provision_delay_s)
        self._activate(slice_id)

    def _activate(self, slice_id):
        with self._lock:
            rec = self._slices.get(slice_id)
            if rec is None or rec["state"] == DELETING:
                return
        if self.gcs_address:
            # back every host with a real node process, stamping the
            # slice-topology env the scheduler's contiguous-ICI packing
            # reads (gcs.py _place_on_contiguous_slice). Re-check
            # liveness around each spawn: delete_slice racing this loop
            # must not leave orphan node processes it can't see.
            for i, host in enumerate(rec["hosts"]):
                with self._lock:
                    if self._slices.get(slice_id) is not rec or \
                            rec["state"] == DELETING:
                        return
                proc, node_id = self._spawn_host(rec, i)
                with self._lock:
                    gone = (self._slices.get(slice_id) is not rec
                            or rec["state"] == DELETING)
                    if not gone:
                        host["proc"] = proc
                        host["node_id"] = node_id
                if gone:
                    proc.terminate()
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                    return
        with self._lock:
            if rec["state"] != DELETING:
                rec["state"] = ACTIVE

    def _spawn_host(self, rec: dict, index: int):
        cfg = rec["node_config"]
        resources = dict(cfg.get("resources") or {})
        num_cpus = int(resources.pop("CPU", 1))
        resources.pop("memory", None)
        ready = f"/tmp/ray_tpu/qrready_{os.getpid()}_{time.time_ns()}"
        env = dict(os.environ,
                   TPU_NAME=rec["name"],
                   TPU_WORKER_ID=str(index),
                   TPU_TOPOLOGY=rec["topology"] or "")
        args = [sys.executable, "-m", "ray_tpu.scripts.node",
                "--address", self.gcs_address,
                "--num-cpus", str(num_cpus),
                "--ready-file", ready,
                "--object-store-memory",
                str(cfg.get("object_store_memory", 64 * 1024 * 1024))]
        if resources:
            args += ["--resources", json.dumps(resources)]
        proc = subprocess.Popen(args, stdout=subprocess.DEVNULL,
                                stderr=subprocess.DEVNULL, env=env,
                                start_new_session=True)
        deadline = time.time() + 60
        node_id = None
        while time.time() < deadline:
            if os.path.exists(ready):
                with open(ready) as f:
                    node_id = json.load(f)["node_id"]
                os.unlink(ready)
                break
            if proc.poll() is not None:
                raise RuntimeError(
                    f"mock TPU host {rec['name']}-w{index} died on start")
            time.sleep(0.05)
        return proc, node_id


class TpuApiError(RuntimeError):
    """A GCE QR call failed terminally (after any retries)."""

    def __init__(self, status: int, message: str):
        self.status = status
        super().__init__(f"TPU API error {status}: {message}")


class TpuAuthError(TpuApiError):
    """401/403 — bad or missing credentials; never retried."""


class TpuQuotaError(TpuApiError):
    """429 that outlived the retry budget (QR quota exhaustion)."""


class GceTpuApi(TpuApi):
    """The real GCE queued-resources API (tpu.googleapis.com v2alpha1).

    Builds the exact REST bodies/URLs and issues them through two
    injectable seams so the whole path is testable against canned
    responses (tests/test_tpu_provider.py replay fixtures) and
    deployable without code changes:

    - ``http(method, url, body_bytes, headers) -> (status, body_bytes)``
      — the transport. Defaults to urllib; tests inject a recorder.
    - ``token_provider() -> str`` — OAuth2 bearer token source.
      Defaults to the GCE metadata server (the only ambient credential
      on a TPU VM); tests inject a stub.

    ``_execute`` layers the control-plane policy on top: every request
    carries the bearer token, 429/503 (and 500) retry with full-jitter
    backoff under the unified RetryPolicy, 401/403 map to TpuAuthError
    with NO retry (re-sending bad credentials just burns quota), a 429
    that outlives the budget maps to TpuQuotaError, DELETE 404 is
    swallowed (releasing an already-released slice is a no-op — the
    provider's terminate path double-asks by design), and any other
    non-2xx maps to TpuApiError carrying the server's error message.
    Reference request shape: autoscaler/_private/gcp/node.py
    create_instance + the QR API docs' tpu.nodeSpec form.
    """

    API_ROOT = "https://tpu.googleapis.com/v2alpha1"
    METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata"
                          "/v1/instance/service-accounts/default/token")
    RETRY_STATUSES = (429, 500, 503)

    def __init__(self, project: str, zone: str,
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 token_provider=None, http=None):
        self.project = project
        self.zone = zone
        self.runtime_version = runtime_version
        self._parent = f"projects/{project}/locations/{zone}"
        self._token_provider = token_provider
        self._http = http if http is not None else self._urllib_http
        self._token_cache: tuple[str, float] | None = None

    def create_slice(self, name, accelerator_type, topology, hosts,
                     node_config):
        body = {
            "tpu": {
                "node_spec": [{
                    "parent": self._parent,
                    "node_id": name,
                    "node": {
                        "accelerator_type": accelerator_type,
                        "runtime_version": node_config.get(
                            "runtimeVersion", self.runtime_version),
                        "network_config": node_config.get(
                            "networkConfig",
                            {"enable_external_ips": False}),
                        "metadata": node_config.get("metadata", {}),
                    },
                }],
            },
        }
        if topology:
            body["tpu"]["node_spec"][0]["node"]["accelerator_config"] = {
                "type": "V5LITE_POD", "topology": topology}
        if node_config.get("schedulingConfig", {}).get("preemptible"):
            body["best_effort"] = {}
        self._execute("POST",
                      f"{self._parent}/queuedResources"
                      f"?queued_resource_id={name}", body)
        return name

    def delete_slice(self, slice_id):
        self._execute("DELETE",
                      f"{self._parent}/queuedResources/{slice_id}"
                      f"?force=true", None)

    def list_slices(self):
        resp = self._execute("GET", f"{self._parent}/queuedResources",
                             None) or {}
        out = []
        for qr in resp.get("queuedResources", []):
            state = qr.get("state", {}).get("state", "")
            mapped = (ACTIVE if state == "ACTIVE"
                      else DELETING if state in ("SUSPENDING", "SUSPENDED")
                      else PROVISIONING)
            specs = qr.get("tpu", {}).get("nodeSpec", [])
            hosts = []
            for spec in specs:
                n_hosts = _hosts_for(spec.get("node", {}))
                node_id = spec.get("nodeId", qr.get("name", ""))
                hosts.extend({"host_id": f"{node_id}-w{i}"}
                             for i in range(n_hosts))
            out.append({"slice_id": qr.get("name", "").rsplit("/", 1)[-1],
                        "name": qr.get("name", ""), "state": mapped,
                        "node_type": "tpu_pod", "topology": "",
                        "hosts": hosts})
        return out

    # ---------------------------------------------------------- transport

    @staticmethod
    def _urllib_http(method: str, url: str, body: bytes | None,
                     headers: dict) -> tuple[int, bytes]:
        """Default transport (only touched when no `http` was injected —
        CI never reaches it). Returns (status, body) for ALL statuses so
        _execute owns the error mapping."""
        import urllib.error
        import urllib.request

        req = urllib.request.Request(url, data=body, headers=headers,
                                     method=method)
        try:
            with urllib.request.urlopen(req, timeout=30) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def _token(self) -> str:
        if self._token_provider is not None:
            return self._token_provider()
        # ambient credentials: the GCE/TPU-VM metadata server. Tokens
        # live ~1h (expires_in); cache until near expiry so retries and
        # the autoscaler's reconcile ticks don't double every API call
        # with a metadata round trip.
        cached = self._token_cache
        if cached is not None and time.monotonic() < cached[1]:
            return cached[0]
        status, body = self._http(
            "GET", self.METADATA_TOKEN_URL,
            None, {"Metadata-Flavor": "Google"})
        if status in self.RETRY_STATUSES:
            # metadata-server hiccups are transient (Google's own auth
            # libraries retry them) — surface as retryable, NOT as a
            # credentials problem the operator would chase
            raise TimeoutError(f"metadata server transient {status}")
        if status != 200:
            raise TpuAuthError(
                status, "no token_provider and the metadata server "
                        "returned no default service-account token")
        payload = json.loads(body)
        token = payload["access_token"]
        # refresh 60s early; a missing expires_in means no caching
        ttl = float(payload.get("expires_in", 0)) - 60.0
        if ttl > 0:
            self._token_cache = (token, time.monotonic() + ttl)
        return token

    @staticmethod
    def _error_message(body: bytes) -> str:
        try:
            err = json.loads(body).get("error", {})
            return err.get("message") or err.get("status") or repr(body)
        except Exception:
            return body[:200].decode("utf-8", "replace")

    def _execute(self, method: str, path: str, body: dict | None):
        from ray_tpu._private.retry import RetryPolicy

        url = f"{self.API_ROOT}/{path}"
        payload = (json.dumps(body).encode() if body is not None else None)
        policy = RetryPolicy.from_config()
        last = [None]   # (status, body) of the final attempt

        def attempt(_timeout):
            last[0] = None   # only the FINAL attempt's status may map
            headers = {"Authorization": f"Bearer {self._token()}",
                       "Content-Type": "application/json"}
            try:
                status, resp = self._http(method, url, payload, headers)
            except TimeoutError:
                raise
            except OSError as e:
                # network-level transport failure (URLError: refused /
                # reset / DNS, connect timeout) — exactly the transient
                # class the retry layer absorbs; surfaced as retryable
                raise TimeoutError(f"transport error: {e}") from e
            last[0] = (status, resp)
            if status in self.RETRY_STATUSES:
                # surfaced as TimeoutError so the policy's retry_on can
                # stay exception-typed; mapped to the real error below
                raise TimeoutError(f"retryable status {status}")
            return status, resp

        try:
            # QR mutations replay safely: create is keyed by
            # queued_resource_id (a replay of an applied create returns
            # ALREADY_EXISTS, not a second slice), delete/get are
            # idempotent — so 429/503/500 retry under the policy
            status, resp = policy.run(
                attempt, retry_on=(TimeoutError,))
        except TimeoutError as e:
            if last[0] is None:
                # the transport itself failed on the final attempt
                # (socket.timeout IS TimeoutError; URLError/metadata
                # hiccups are re-surfaced as one) — no HTTP status to map
                raise TpuApiError(
                    0, f"transport failure talking to {url}: {e}") from e
            status, resp = last[0]   # retries exhausted on 429/500/503
        if 200 <= status < 300:
            return json.loads(resp) if resp else {}
        message = self._error_message(resp)
        if status in (401, 403):
            raise TpuAuthError(status, message)
        if status == 429:
            raise TpuQuotaError(status, f"QUOTA_EXHAUSTED: {message}")
        if status == 404 and method == "DELETE":
            return {}   # releasing an already-released slice is a no-op
        if status == 409 and method == "POST":
            # ALREADY_EXISTS: our earlier attempt was applied before its
            # reply was lost (the very replay the retry comment above
            # relies on) — the slice exists, so the create SUCCEEDED
            return {}
        raise TpuApiError(status, message)


def _hosts_for(node: dict) -> int:
    """Host count of a slice from its accelerator type/topology: chips
    from the topology product (or the vN-<chips> suffix), 4 chips per
    host on v4/v5 pods, 8 on v5e single-host types."""
    accel = node.get("accelerator_type", "")
    topo = node.get("accelerator_config", {}).get("topology", "")
    if topo:
        chips = 1
        for d in topo.split("x"):
            chips *= int(d)
        return max(1, chips // 4)
    if "-" in accel:
        chips = int(accel.rsplit("-", 1)[1])
        return max(1, chips // 8)
    return 1
