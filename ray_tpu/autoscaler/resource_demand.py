"""Shape-aware demand binpacking for the autoscaler.

Reference: python/ray/autoscaler/_private/resource_demand_scheduler.py —
`get_nodes_to_launch` (:103) binpacks queued resource shapes (including
placement-group bundles with their strategies, :171) onto current node
headroom, then onto virtual nodes of the configured types, scoring
candidate types by utilization so the cheapest-fitting type wins.

TPU-native extension: a node type may describe a multi-host TPU slice
(``"tpu_slice": {"topology": "4x4", "hosts": 4}``) — its `resources` are
PER-HOST and the slice is created as a unit (QR-style "give me a slice
of topology X"), so plans count slice types in slice units and the
provider's ``create_slice`` launches all member hosts atomically. This
is what the reference's flat `resources: {"TPU": 4}` GCP config
(autoscaler/gcp/tpu.yaml:29) cannot express.

Pure functions — no cluster dependencies; the StandardAutoscaler feeds
them GCS load and executes the returned plan.
"""
from __future__ import annotations


def expand_pg_demand(pending_pgs: list[dict]) -> list[dict]:
    """Flatten pending placement groups into placeable shapes with
    placement constraints (reference: resource_demand_scheduler.py:171
    placement_groups_to_resource_demands):

    - STRICT_PACK: all bundles must land on ONE node -> a single summed
      shape.
    - STRICT_SPREAD: each bundle on a DISTINCT node -> shapes sharing an
      ``anti_affinity`` group id.
    - PACK / SPREAD: best-effort -> plain shapes.

    Returns [{"shape": {...}, "anti_affinity": str|None}].
    """
    out = []
    for i, pg in enumerate(pending_pgs):
        strategy = pg.get("strategy", "PACK")
        bundles = [dict(b) for b in pg.get("bundles", []) if b]
        if not bundles:
            continue
        if strategy == "STRICT_PACK":
            combined: dict = {}
            for b in bundles:
                for k, v in b.items():
                    combined[k] = combined.get(k, 0) + v
            out.append({"shape": combined, "anti_affinity": None})
        elif strategy == "STRICT_SPREAD":
            gid = pg.get("pg_id", f"pg-{i}")
            for b in bundles:
                out.append({"shape": b, "anti_affinity": gid})
        else:
            for b in bundles:
                out.append({"shape": b, "anti_affinity": None})
    return out


def _fits(avail: dict, shape: dict) -> bool:
    return all(avail.get(k, 0) + 1e-9 >= v for k, v in shape.items())


def _take(avail: dict, shape: dict):
    for k, v in shape.items():
        avail[k] = avail.get(k, 0) - v


def utilization_score(node_resources: dict, shapes: list[dict]):
    """Score a node type for hosting `shapes` (higher wins). Reference
    `_utilization_score`: prefer types the demand utilizes tightly, and
    avoid parking non-TPU work on TPU nodes (the reference's GPU
    avoidance, scheduler flavor) so accelerator capacity stays free for
    accelerator demand. Returns None if the type fits none of them."""
    avail = dict(node_resources)
    placed = []
    for entry in sorted(shapes, key=_shape_size, reverse=True):
        if _fits(avail, entry):
            _take(avail, entry)
            placed.append(entry)
    if not placed:
        return None
    wants_tpu = any("TPU" in s for s in placed)
    if node_resources.get("TPU", 0) > 0 and not wants_tpu:
        return (0, 0.0, 0.0)   # feasible, but a last resort
    util = []
    for k, total in node_resources.items():
        if total <= 0:
            continue
        used = total - avail.get(k, 0)
        if used > 0:
            util.append(used / total)
    score = (len(placed),
             min(util) if util else 0.0,
             sum(util) / len(util) if util else 0.0)
    return score


def _shape_size(entry) -> tuple:
    shape = entry["shape"] if "shape" in entry else entry
    return (shape.get("TPU", 0), shape.get("CPU", 0),
            sum(shape.values()))


def get_nodes_to_launch(task_shapes: list[dict],
                        pending_pgs: list[dict],
                        headroom: list[dict],
                        node_types: dict[str, dict],
                        counts_by_type: dict[str, int] | None = None,
                        max_workers: int = 8):
    """Plan node launches covering unfulfilled demand.

    Returns (plan, infeasible): plan is {node_type: count} — count in
    SLICE units for slice types, hosts otherwise; infeasible lists
    shapes no configured type can ever host (surfaced to the user, as
    the reference logs them).
    """
    counts_by_type = dict(counts_by_type or {})
    demands = [{"shape": dict(s), "anti_affinity": None}
               for s in task_shapes if s]
    demands += expand_pg_demand(pending_pgs)
    demands.sort(key=_shape_size, reverse=True)

    # 1. absorb into existing headroom (anti-affinity groups need
    #    distinct nodes, so remember which group used which node)
    nodes = [{"avail": dict(h), "groups": set()} for h in headroom]
    unfulfilled = []
    for entry in demands:
        placed = False
        for node in nodes:
            if (entry["anti_affinity"] is not None
                    and entry["anti_affinity"] in node["groups"]):
                continue
            if _fits(node["avail"], entry["shape"]):
                _take(node["avail"], entry["shape"])
                if entry["anti_affinity"] is not None:
                    node["groups"].add(entry["anti_affinity"])
                placed = True
                break
        if not placed:
            unfulfilled.append(entry)

    # 2. binpack the rest onto virtual nodes of the best-scoring types
    plan: dict[str, int] = {}
    virtual: list[dict] = []   # {"type", "avail", "groups"}
    infeasible = []

    def _hosts_per_unit(tname):
        if tname not in node_types:
            return 1
        return int((node_types[tname].get("tpu_slice") or {})
                   .get("hosts", 1))

    # counts, per-type caps and the global max_workers budget are all in
    # HOSTS (what provider.non_terminated_nodes lists); the returned plan
    # counts slice types in SLICE units (what create_slice launches)
    total_existing = sum(counts_by_type.values())

    def _planned_hosts():
        return sum(c * _hosts_per_unit(t) for t, c in plan.items())

    for entry in unfulfilled:
        placed = False
        for node in virtual:
            if (entry["anti_affinity"] is not None
                    and entry["anti_affinity"] in node["groups"]):
                continue
            if _fits(node["avail"], entry["shape"]):
                _take(node["avail"], entry["shape"])
                if entry["anti_affinity"] is not None:
                    node["groups"].add(entry["anti_affinity"])
                placed = True
                break
        if placed:
            continue
        # pick the best feasible type for this shape (score it together
        # with everything else still unplaced of the same look — cheap
        # approximation of the reference's per-type utilization pass)
        best = None
        for tname, spec in node_types.items():
            res = spec.get("resources", {})
            score = utilization_score(res, [entry["shape"]])
            if score is None:
                continue
            cap = spec.get("max_workers", max_workers)   # hosts
            planned_hosts_t = plan.get(tname, 0) * _hosts_per_unit(tname)
            if (counts_by_type.get(tname, 0) + planned_hosts_t
                    + _hosts_per_unit(tname)) > cap:
                continue
            if (total_existing + _planned_hosts()
                    + _hosts_per_unit(tname)) > max_workers:
                continue
            if best is None or score > best[0]:
                best = (score, tname)
        if best is None:
            infeasible.append(entry["shape"])
            continue
        tname = best[1]
        spec = node_types[tname]
        plan[tname] = plan.get(tname, 0) + 1
        # slice units contribute every member host's headroom
        for _ in range(_hosts_per_unit(tname)):
            virtual.append({"type": tname,
                            "avail": dict(spec.get("resources", {})),
                            "groups": set()})
        node = next(v for v in reversed(virtual)
                    if _fits(v["avail"], entry["shape"]))
        _take(node["avail"], entry["shape"])
        if entry["anti_affinity"] is not None:
            node["groups"].add(entry["anti_affinity"])
    return plan, infeasible
