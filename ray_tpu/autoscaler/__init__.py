"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference: python/ray/autoscaler/ (~21.7k LoC; SURVEY.md §2.2):
StandardAutoscaler.update (autoscaler.py:162,353) reading LoadMetrics from
the GCS, ResourceDemandScheduler binpacking demand onto node types
(resource_demand_scheduler.py:103,171), and the NodeProvider plugin API
(node_provider.py). Ours keeps the same three pieces: GCS `get_cluster_load`
is the LoadMetrics source, `StandardAutoscaler.update()` binpacks queued
demand + pending PG bundles, and providers plug in node create/terminate —
`LocalNodeProvider` spawns real OS node processes (the fake-multinode test
analog), a TPU pod provider slots in the same API for GCE/QR.
"""
from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.node_provider import LocalNodeProvider, NodeProvider
from ray_tpu.autoscaler.tpu_provider import (MockTpuApi, TpuApi,
                                             TPUPodNodeProvider)

__all__ = ["LocalNodeProvider", "MockTpuApi", "NodeProvider",
           "StandardAutoscaler", "TpuApi", "TPUPodNodeProvider"]
