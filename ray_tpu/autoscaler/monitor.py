"""Autoscaler monitor process: `python -m ray_tpu.autoscaler.monitor`.

Reference: python/ray/autoscaler/_private/monitor.py — a standalone
process on the head node owning the NodeProvider and driving
StandardAutoscaler.update() on an interval. SIGTERM releases every
provider node/slice before exit (`ray down` relies on this: worker VMs
belong to the provider in THIS process).
"""
from __future__ import annotations

import argparse
import signal
import threading


def main(argv=None):
    p = argparse.ArgumentParser(prog="ray_tpu.autoscaler.monitor")
    p.add_argument("--config", required=True, help="cluster YAML path")
    p.add_argument("--gcs-address", required=True)
    p.add_argument("--interval-s", type=float, default=5.0)
    args = p.parse_args(argv)

    from ray_tpu.autoscaler import StandardAutoscaler
    from ray_tpu.autoscaler.launcher import (load_cluster_config,
                                             make_provider)

    cfg = load_cluster_config(args.config)
    provider = make_provider(cfg, args.gcs_address)
    head_type = cfg.get("head_node_type")
    worker_types = {
        name: spec for name, spec in cfg["available_node_types"].items()
        if name != head_type
    }
    autoscaler = StandardAutoscaler(
        args.gcs_address,
        {"max_workers": cfg.get("max_workers", 8),
         "min_workers": cfg.get("min_workers", 0),
         "idle_timeout_s": cfg.get("idle_timeout_s", 60.0),
         "available_node_types": worker_types},
        provider)

    stop = threading.Event()

    def _term(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    # min_workers launch immediately (reference: the monitor's first
    # update brings the cluster to min size before any demand exists)
    for name, spec in worker_types.items():
        for _ in range(int(spec.get("min_workers", 0))):
            try:
                if spec.get("tpu_slice"):
                    provider.create_slice(
                        name, spec, spec["tpu_slice"].get("topology", ""))
                else:
                    provider.create_node(name, spec, 1)
            except Exception:
                pass

    while not stop.is_set():
        try:
            autoscaler.update()
        except Exception:
            pass
        stop.wait(args.interval_s)

    autoscaler.stop()
    try:
        provider.shutdown()
    except Exception:
        pass


if __name__ == "__main__":
    main()
