"""NodeProvider plugin API + the local (process-spawning) provider.

Reference: python/ray/autoscaler/node_provider.py (create_node /
terminate_node / non_terminated_nodes) and the fake_multi_node provider
used by autoscaler tests. LocalNodeProvider launches real
`ray_tpu.scripts.node` OS processes joining the GCS — the closest analog
of a cloud VM on one machine.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time


class NodeProvider:
    """Provider contract. node_type → the key in the autoscaler config's
    available_node_types whose `resources` the node advertises."""

    def non_terminated_nodes(self) -> list[dict]:
        """[{provider_id, node_type, node_id (runtime id, once known)}]"""
        raise NotImplementedError

    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> list[str]:
        raise NotImplementedError

    def create_slice(self, node_type: str, node_config: dict,
                     topology: str) -> list[str]:
        """Create one multi-host TPU slice as a unit — the QR-style
        "give me a slice of topology X" call (reference: the GCP
        provider's flat tpu.yaml cannot express this; queued-resources
        APIs can). The DEFAULT merely launches the member hosts as
        ordinary nodes (correct count, no shared slice identity): real
        TPU providers must override this with their slice/QR API, which
        is what stamps TPU_NAME/TPU_WORKER_ID/TPU_TOPOLOGY on the VMs
        (detect_tpu_topology reads those to advertise slice structure)."""
        hosts = int((node_config.get("tpu_slice") or {}).get("hosts", 1))
        return self.create_node(node_type, node_config, hosts)

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    def __init__(self, gcs_address: str):
        self.gcs_address = gcs_address
        self._nodes: dict[str, dict] = {}   # provider_id -> info

    def non_terminated_nodes(self) -> list[dict]:
        out = []
        for pid, info in list(self._nodes.items()):
            if info["proc"].poll() is not None:
                del self._nodes[pid]
                continue
            out.append({"provider_id": pid,
                        "node_type": info["node_type"],
                        "node_id": info.get("node_id")})
        return out

    def create_node(self, node_type: str, node_config: dict,
                    count: int) -> list[str]:
        created = []
        for _ in range(count):
            ready = f"/tmp/ray_tpu/asready_{os.getpid()}_{time.time_ns()}"
            resources = dict(node_config.get("resources") or {})
            num_cpus = int(resources.pop("CPU", 1))
            args = [sys.executable, "-m", "ray_tpu.scripts.node",
                    "--address", self.gcs_address,
                    "--num-cpus", str(num_cpus),
                    "--ready-file", ready,
                    "--object-store-memory",
                    str(node_config.get("object_store_memory",
                                        64 * 1024 * 1024))]
            resources.pop("memory", None)
            if resources:
                args += ["--resources", json.dumps(resources)]
            proc = subprocess.Popen(args, stdout=subprocess.DEVNULL,
                                    stderr=subprocess.DEVNULL,
                                    start_new_session=True)
            deadline = time.time() + 60
            node_id = None
            while time.time() < deadline:
                if os.path.exists(ready):
                    with open(ready) as f:
                        node_id = json.load(f)["node_id"]
                    os.unlink(ready)
                    break
                if proc.poll() is not None:
                    raise RuntimeError("autoscaled node died during start")
                time.sleep(0.05)
            provider_id = f"local-{proc.pid}"
            self._nodes[provider_id] = {"proc": proc,
                                        "node_type": node_type,
                                        "node_id": node_id}
            created.append(provider_id)
        return created

    def terminate_node(self, provider_id: str) -> None:
        info = self._nodes.pop(provider_id, None)
        if info is None:
            return
        proc = info["proc"]
        if proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except OSError:
                pass
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def shutdown(self):
        for pid in list(self._nodes):
            self.terminate_node(pid)
