"""ObjectRef — a future naming an immutable object in the cluster.

Analog of the reference's ObjectRef (python/ray/includes/object_ref.pxi,
ownership model in src/ray/core_worker/reference_count.h): every ref carries
its id and the address of its *owner* (the worker that submitted the creating
task or called put), which is the authority for its value/location.
"""
from __future__ import annotations

import os
import threading


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_worker", "__weakref__")

    def __init__(self, object_id: bytes, owner_addr=None, worker=None):
        assert isinstance(object_id, bytes) and len(object_id) == 16
        self.id = object_id
        self.owner_addr = tuple(owner_addr) if owner_addr else None
        # The core worker that materialized this ref in this process; used
        # for ref-counting on GC. Set by serialization on inbound refs.
        self._worker = worker
        if worker is not None:
            worker.reference_counter.add_local_ref(self.id)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id

    @staticmethod
    def nil() -> "ObjectRef":
        return ObjectRef(b"\0" * 16)

    @staticmethod
    def from_random() -> "ObjectRef":
        return ObjectRef(os.urandom(16))

    def future(self):
        """concurrent.futures-style future for await/as_completed interop."""
        from ray_tpu._private import api

        return api.get_runtime_context()._worker.as_future(self)

    def __reduce__(self):
        # Refs travel as (id, owner); the receiving process re-binds them to
        # its own core worker via serialization context (never naive unpickle
        # into a dead ref).
        return (_rebuild_ref, (self.id, self.owner_addr))

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        worker = self._worker
        if worker is not None:
            try:
                worker.reference_counter.remove_local_ref(self.id)
            except Exception:
                pass

    # Explicitly not awaitable/iterable to fail fast on common misuse.
    def __iter__(self):
        raise TypeError(
            "ObjectRef is not iterable; call ray_tpu.get(ref) first")


def _rebuild_ref(object_id: bytes, owner_addr):
    from ray_tpu._private.worker_runtime import current_worker

    worker = current_worker()
    return ObjectRef(object_id, owner_addr, worker)


class ReferenceCounter:
    """Process-local ref counting feeding the distributed release protocol.

    Simplified from the reference's owner/borrower protocol
    (src/ray/core_worker/reference_count.h): each process counts its local
    Python refs per object id; when an id's count drops to zero the worker
    notifies the owner, which frees the primary copy once all holders have
    released. Lineage pinning is not implemented (objects are not
    reconstructable in v1 — fetch failures raise ObjectLostError).
    """

    def __init__(self, on_zero=None):
        self._counts: dict[bytes, int] = {}
        self._lock = threading.Lock()
        self._on_zero = on_zero

    def add_local_ref(self, object_id: bytes):
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1

    def remove_local_ref(self, object_id: bytes):
        notify = False
        with self._lock:
            n = self._counts.get(object_id)
            if n is None:
                return
            if n <= 1:
                del self._counts[object_id]
                notify = True
            else:
                self._counts[object_id] = n - 1
        if notify and self._on_zero is not None:
            self._on_zero(object_id)

    def count(self, object_id: bytes) -> int:
        with self._lock:
            return self._counts.get(object_id, 0)

    def held_ids(self):
        with self._lock:
            return list(self._counts)
