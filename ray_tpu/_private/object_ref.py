"""ObjectRef — a future naming an immutable object in the cluster.

Analog of the reference's ObjectRef (python/ray/includes/object_ref.pxi,
ownership model in src/ray/core_worker/reference_count.h): every ref carries
its id and the address of its *owner* (the worker that submitted the creating
task or called put), which is the authority for its value/location.
"""
from __future__ import annotations

import os
import threading


class ObjectRef:
    __slots__ = ("id", "owner_addr", "_worker", "__weakref__")

    def __init__(self, object_id: bytes, owner_addr=None, worker=None):
        assert isinstance(object_id, bytes) and len(object_id) == 16
        self.id = object_id
        self.owner_addr = tuple(owner_addr) if owner_addr else None
        # The core worker that materialized this ref in this process; used
        # for ref-counting on GC. Set by serialization on inbound refs.
        self._worker = worker
        if worker is not None:
            worker.reference_counter.add_local_ref(self.id)

    def hex(self) -> str:
        return self.id.hex()

    def binary(self) -> bytes:
        return self.id

    @staticmethod
    def nil() -> "ObjectRef":
        return ObjectRef(b"\0" * 16)

    @staticmethod
    def from_random() -> "ObjectRef":
        return ObjectRef(os.urandom(16))

    def future(self):
        """concurrent.futures-style future for await/as_completed interop."""
        from ray_tpu._private import api

        return api.get_runtime_context()._worker.as_future(self)

    def __reduce__(self):
        # Refs travel as (id, owner); the receiving process re-binds them to
        # its own core worker via serialization context (never naive unpickle
        # into a dead ref).
        return (_rebuild_ref, (self.id, self.owner_addr))

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id.hex()})"

    def __del__(self):
        worker = self._worker
        if worker is not None:
            try:
                worker.reference_counter.remove_local_ref(self.id)
            except Exception:
                pass

    # Explicitly not awaitable/iterable to fail fast on common misuse.
    def __iter__(self):
        raise TypeError(
            "ObjectRef is not iterable; call ray_tpu.get(ref) first")


def _rebuild_ref(object_id: bytes, owner_addr):
    from ray_tpu._private.worker_runtime import current_worker

    worker = current_worker()
    return ObjectRef(object_id, owner_addr, worker)


class ObjectRefGenerator:
    """Iterator over the refs of a dynamic-returns task.

    Analog of the reference's ObjectRefGenerator
    (python/ray/_raylet.pyx:168): a task declared
    ``num_returns="dynamic"`` yields values, each stored as its own
    object; the task's single return ref resolves to this generator.
    With ``num_returns="streaming"`` the generator comes back from
    ``.remote()`` directly and can be consumed WHILE the task is still
    producing — ``__next__`` blocks until the next item is announced.

    Two modes share this class:
    - *static* (``_refs`` known): rebuilt from a completed task's
      return value; iteration never blocks.
    - *live* (``_stream`` bound): created at submission in streaming
      mode; iteration waits on the owner-side stream that the
      executor's per-item announcements feed.
    """

    def __init__(self, gen_id: bytes, owner_addr=None, item_ids=None,
                 worker=None):
        self._gen_id = gen_id
        self._owner_addr = tuple(owner_addr) if owner_addr else None
        self._worker = worker
        self._item_ids = list(item_ids) if item_ids is not None else None
        self._cursor = 0
        self._closed = False
        # hold a local ref on the generator object itself so the task's
        # lineage/result stays alive while the generator is
        self._gen_ref = ObjectRef(gen_id, owner_addr, worker)

    # -- iteration -----------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        if self._closed:
            raise StopIteration
        if self._item_ids is not None:
            if self._cursor >= len(self._item_ids):
                raise StopIteration
            rid = self._item_ids[self._cursor]
        else:
            rid = self._worker._gen_next(self._gen_id, self._cursor)
            if rid is None:
                raise StopIteration
        self._cursor += 1
        return ObjectRef(rid, self._owner_addr, self._worker)

    def __len__(self):
        if self._item_ids is not None:
            return len(self._item_ids)
        n = self._worker._gen_total(self._gen_id)
        if n is None:
            raise TypeError(
                "len() on a streaming ObjectRefGenerator whose task is "
                "still producing; iterate it or wait on completed()")
        return n

    # -- control -------------------------------------------------------------
    def completed(self) -> ObjectRef:
        """Ref that resolves when the producing task finishes (its value
        is this generator in static form)."""
        return self._gen_ref

    def close(self):
        """Stop consuming: cancels the producing task if it is still
        running (reference: deleting/closing a streaming generator
        cancels the task)."""
        if self._closed:
            return
        self._closed = True
        if self._item_ids is None and self._worker is not None:
            self._worker._close_gen(self._gen_ref)

    def __del__(self):
        # NO locks, NO network here: GC can run this at any bytecode
        # boundary (see CoreWorker._on_local_refs_zero). Dropping
        # self._gen_ref enqueues the free; the reaper thread cancels a
        # still-running producer inside _free_object.
        self._closed = True

    def __reduce__(self):
        if self._item_ids is None:
            raise TypeError(
                "a streaming ObjectRefGenerator cannot be serialized; "
                "pass the individual ObjectRefs instead")
        return (_rebuild_gen, (self._gen_id, self._owner_addr,
                               list(self._item_ids)))

    def __repr__(self):
        mode = ("static" if self._item_ids is not None else "streaming")
        return f"ObjectRefGenerator({self._gen_id.hex()}, {mode})"


def _rebuild_gen(gen_id: bytes, owner_addr, item_ids):
    from ray_tpu._private.worker_runtime import current_worker

    return ObjectRefGenerator(gen_id, owner_addr, item_ids,
                              current_worker())


class ReferenceCounter:
    """Process-local ref counting feeding the distributed release protocol.

    Simplified from the reference's owner/borrower protocol
    (src/ray/core_worker/reference_count.h): each process counts its local
    Python refs per object id; when an id's count drops to zero the worker
    notifies the owner, which frees the primary copy once all holders have
    released. Lineage pinning is not implemented (objects are not
    reconstructable in v1 — fetch failures raise ObjectLostError).
    """

    def __init__(self, on_zero=None):
        import queue

        self._counts: dict[bytes, int] = {}
        self._lock = threading.Lock()
        self._on_zero = on_zero
        # remove_local_ref runs from ObjectRef.__del__, which the GC can
        # fire at ANY bytecode boundary — including INSIDE add_local_ref
        # while this thread already holds the (non-reentrant) lock
        # above. Taking the lock there self-deadlocks (observed: a
        # 10k-ref release storm wedging the next 5000-return submit).
        # So __del__ only ENQUEUES (SimpleQueue.put is reentrancy-safe
        # by design); this drainer does the locked decrement.
        self._defer_q: "queue.SimpleQueue" = queue.SimpleQueue()
        self._drainer = threading.Thread(target=self._drain, daemon=True,
                                         name="refcount-drainer")
        self._drainer.start()

    def add_local_ref(self, object_id: bytes):
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1

    def remove_local_ref(self, object_id: bytes):
        """GC-safe: enqueue only (see __init__). Decrements lag
        increments by one queue hop — the safe direction (frees are
        delayed, never premature)."""
        self._defer_q.put(object_id)

    def shutdown(self):
        """Stop the drainer (its bound-method target would otherwise pin
        the whole owning worker graph alive forever)."""
        self._defer_q.put(None)

    def _drain(self):
        while True:
            object_id = self._defer_q.get()
            if object_id is None:
                return
            notify = False
            with self._lock:
                n = self._counts.get(object_id)
                if n is None:
                    continue
                if n <= 1:
                    del self._counts[object_id]
                    notify = True
                else:
                    self._counts[object_id] = n - 1
            if notify and self._on_zero is not None:
                try:
                    self._on_zero(object_id)
                except Exception:
                    pass

    def count(self, object_id: bytes) -> int:
        with self._lock:
            return self._counts.get(object_id, 0)

    def held_ids(self):
        with self._lock:
            return list(self._counts)
