"""ctypes bindings for the native RPC core (src/rpc/rpc_core.cc).

Drop-in replacements for protocol.PyRpcClient / PyRpcServer: framing,
connection management, reply correlation and the request queue run in
C++ threads with no GIL involvement; Python handles pickle and handler
dispatch. Reference split: src/ray/rpc/ GrpcServer + ClientCallManager
under a thin Cython shim (_raylet.pyx) — compiled transport, interpreted
policy.

Selection happens in protocol.RpcClient/RpcServer (env
RAY_TPU_NATIVE_RPC=0 forces the pure-Python path).
"""
from __future__ import annotations

import ctypes
import os
import pickle
import threading
import time

from ray_tpu._private import fault_injection as _fi
from ray_tpu._private import telemetry as _tm

_REQUEST, _REPLY, _PUSH = 0, 1, 2
_PUSH_OOB = 3   # one-way out-of-band frame (protocol.PUSH_OOB) — the C
                # core treats `kind` opaquely, so no C change is needed
_EV_DISCONNECT, _EV_CONNECT = -1, -2

_lib = None
_lib_lock = threading.Lock()


def load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        from ray_tpu._private.native_build import ensure_lib

        lib = ctypes.CDLL(ensure_lib("rayrpc"))
        lib.rpc_buf_free.restype = None
        # free() must see the ORIGINAL pointer, so buffers travel as
        # c_void_p and are cast for reading
        lib.rpc_buf_free.argtypes = [ctypes.c_void_p]

        lib.rpc_cl_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_int]
        lib.rpc_cl_connect.restype = ctypes.c_void_p
        lib.rpc_cl_send.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                    ctypes.c_longlong, ctypes.c_char_p,
                                    ctypes.c_size_t, ctypes.c_int]
        lib.rpc_cl_send.restype = ctypes.c_int
        lib.rpc_cl_wait.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                    ctypes.c_int,
                                    ctypes.POINTER(ctypes.c_void_p),
                                    ctypes.POINTER(ctypes.c_size_t)]
        lib.rpc_cl_wait.restype = ctypes.c_int
        lib.rpc_cl_abandon.argtypes = [ctypes.c_void_p, ctypes.c_longlong]
        lib.rpc_cl_abandon.restype = None
        lib.rpc_cl_poll_async.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.rpc_cl_poll_async.restype = ctypes.c_int
        lib.rpc_cl_closed.argtypes = [ctypes.c_void_p]
        lib.rpc_cl_closed.restype = ctypes.c_int
        lib.rpc_cl_ver_mismatch.argtypes = [ctypes.c_void_p]
        lib.rpc_cl_ver_mismatch.restype = ctypes.c_int
        lib.rpc_cl_close.argtypes = [ctypes.c_void_p]
        lib.rpc_cl_close.restype = None

        lib.rpc_sv_start.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.rpc_sv_start.restype = ctypes.c_void_p
        lib.rpc_sv_port.argtypes = [ctypes.c_void_p]
        lib.rpc_sv_port.restype = ctypes.c_int
        lib.rpc_sv_next.argtypes = [
            ctypes.c_void_p, ctypes.c_int,
            ctypes.POINTER(ctypes.c_ulonglong),
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_size_t)]
        lib.rpc_sv_next.restype = ctypes.c_int
        lib.rpc_sv_send.argtypes = [ctypes.c_void_p, ctypes.c_ulonglong,
                                    ctypes.c_int, ctypes.c_longlong,
                                    ctypes.c_char_p, ctypes.c_size_t]
        lib.rpc_sv_send.restype = ctypes.c_int
        lib.rpc_sv_conn_alive.argtypes = [ctypes.c_void_p,
                                          ctypes.c_ulonglong]
        lib.rpc_sv_conn_alive.restype = ctypes.c_int
        lib.rpc_sv_close_conn.argtypes = [ctypes.c_void_p,
                                          ctypes.c_ulonglong]
        lib.rpc_sv_close_conn.restype = None
        lib.rpc_sv_stop.argtypes = [ctypes.c_void_p]
        lib.rpc_sv_stop.restype = None
        _lib = lib
        return lib


def _take_buf(lib, ptr, length) -> bytes:
    try:
        return ctypes.string_at(ptr, length) if length else b""
    finally:
        lib.rpc_buf_free(ptr)


# top-level import is cycle-safe: protocol only imports native_rpc
# lazily inside functions (load_lib / the transport factories)
from ray_tpu._private.protocol import OobFrame as _OobFrame  # noqa: E402


class _NativeOobFrame(_OobFrame):
    """protocol.OobFrame (isinstance-compatible — consumers type-check
    against the base) over the C reader's malloc'd payload: the tensor
    body is consumed as a zero-copy view of the C buffer (no string_at
    copy per segment); release() frees it exactly once. A dropped frame
    (handler bug) leaks its buffer — the same contract as the pooled
    Python frames, which just lose a pool slot."""

    __slots__ = ("_lib", "_ptr", "_mem")

    def __init__(self, lib, ptr, length):   # noqa: super-init not useful
        self._lib = lib
        self._ptr = ptr
        self._mem = memoryview(
            (ctypes.c_char * length).from_address(ptr)).cast("B")
        self.view = None   # body view, set by parse_head

    def parse_head(self):
        import struct

        (head_len,) = struct.unpack_from(">I", self._mem, 0)
        method, kwargs, _pool = pickle.loads(self._mem[4:4 + head_len])
        self.view = self._mem[4 + head_len:]
        return method, kwargs

    @property
    def nbytes(self) -> int:
        return self.view.nbytes if self.view is not None else 0

    def release(self):
        ptr, self._ptr = self._ptr, None
        if ptr is not None:
            # drop every export of the ctypes memory before freeing —
            # a live memoryview over freed heap would be use-after-free
            self.view = None
            self._mem.release()
            self._mem = None
            self._lib.rpc_buf_free(ptr)


class NativeRpcClient:
    """protocol.PyRpcClient-compatible client over the C core."""

    def __init__(self, addr, timeout: float = 30.0, on_push=None,
                 retry: int = 3):
        from ray_tpu._private.protocol import ConnectionLost
        from ray_tpu._private.retry import RetryPolicy

        self.addr = tuple(addr)
        self._timeout = timeout   # None = calls block until reply/close
        self._on_push = on_push
        self._lib = load_lib()
        connect_ms = int((timeout if timeout is not None else 30.0) * 1000)
        policy = RetryPolicy(max_attempts=retry, deadline_s=None)
        handle = None
        for attempt in range(retry):
            handle = self._lib.rpc_cl_connect(
                str(self.addr[0]).encode(), int(self.addr[1]), connect_ms)
            if handle:
                break
            if attempt + 1 < retry:
                time.sleep(policy.backoff(attempt + 1))
        if not handle:
            raise ConnectionLost(f"cannot connect to {self.addr}")
        self._h = handle
        self._seq = 0
        self._seq_lock = threading.Lock()
        self._pending: dict[int, object] = {}
        self._pending_lock = threading.Lock()
        self._closed = False
        self._close_lock = threading.Lock()
        self._pump = None
        if on_push is not None:
            self._ensure_pump()

    def _next_seq(self) -> int:
        with self._seq_lock:
            self._seq += 1
            return self._seq

    def _lost_error(self):
        """ConnectionLost — or the NAMED ProtocolMismatch when the C reader
        dropped the connection over a wire-revision disagreement."""
        from ray_tpu._private.protocol import ConnectionLost, ProtocolMismatch

        if self._lib.rpc_cl_ver_mismatch(self._h):
            return ProtocolMismatch(
                f"rpc protocol version mismatch with {self.addr} — both "
                f"ends of a cluster must run the same ray-tpu wire revision")
        return ConnectionLost(f"connection to {self.addr} lost")

    # ------------------------------------------------------------- sync path
    def call(self, method: str, timeout: float | None = None, **kwargs):
        from ray_tpu._private.protocol import _RemoteError

        if self._closed:
            raise self._lost_error()
        start = time.monotonic() if _tm.ENABLED else 0.0
        t = timeout if timeout is not None else self._timeout
        inj = _fi.ACTIVE
        plan = inj.on_send(method) if inj is not None else None
        if plan is not None:
            try:
                _fi.apply_send_plan(plan, self.close, method)
            except BaseException:
                # injected disconnect raises ConnectionLost at send time
                self._count_error(method, "connection_lost")
                raise
            if plan.drop:
                # injected loss on a sync call: the caller experiences
                # its timeout, exactly as if the frame left and vanished
                # (None-timeout callers get the transport default so the
                # chaos plane can't wedge a process forever)
                time.sleep(t if t is not None else 30.0)
                self._count_error(method, "timeout")
                raise TimeoutError("rpc call timed out")
        seq = self._next_seq()
        payload = pickle.dumps((method, kwargs),
                               protocol=pickle.HIGHEST_PROTOCOL)
        rc = self._lib.rpc_cl_send(self._h, _REQUEST, seq, payload,
                                   len(payload), 1)
        if rc == 0 and plan is not None and plan.dup:
            rc = self._lib.rpc_cl_send(self._h, _REQUEST, seq, payload,
                                       len(payload), 1)
        if rc != 0:
            self._closed = True
            self._count_error(method, "connection_lost")
            raise self._lost_error()
        out = ctypes.c_void_p()
        out_len = ctypes.c_size_t()
        rc = self._lib.rpc_cl_wait(
            self._h, seq, -1 if t is None else int(t * 1000),
            ctypes.byref(out), ctypes.byref(out_len))
        if rc == 1:
            self._lib.rpc_cl_abandon(self._h, seq)
            self._count_error(method, "timeout")
            raise TimeoutError("rpc call timed out")
        if rc != 0:
            self._closed = True
            self._count_error(method, "connection_lost")
            raise self._lost_error()
        result = pickle.loads(_take_buf(self._lib, out, out_len.value))
        if isinstance(result, _RemoteError):
            raise result.exc
        if _tm.ENABLED:
            _tm.observe("ray_tpu_rpc_latency_seconds",
                        time.monotonic() - start,
                        tags={"method": method, "role": _tm.role()})
        return result

    @staticmethod
    def _count_error(method: str, kind: str):
        _tm.counter_inc("ray_tpu_rpc_errors_total", tags={
            "method": method, "role": _tm.role(), "kind": kind})

    # ------------------------------------------------------------ async path
    def call_async(self, method: str, **kwargs):
        from ray_tpu._private.protocol import _Future, _RemoteError

        if self._closed:
            raise self._lost_error()
        inj = _fi.ACTIVE
        plan = inj.on_send(method) if inj is not None else None
        if plan is not None:
            _fi.apply_send_plan(plan, self.close, method)
        self._ensure_pump()
        seq = self._next_seq()
        fut = _Future()
        with self._pending_lock:
            self._pending[seq] = fut
        if plan is not None and plan.drop:
            return fut   # injected message loss: registered, never sent
        payload = pickle.dumps((method, kwargs),
                               protocol=pickle.HIGHEST_PROTOCOL)
        rc = self._lib.rpc_cl_send(self._h, _REQUEST, seq, payload,
                                   len(payload), 0)
        if rc == 0 and plan is not None and plan.dup:
            rc = self._lib.rpc_cl_send(self._h, _REQUEST, seq, payload,
                                       len(payload), 0)
        if rc != 0:
            with self._pending_lock:
                self._pending.pop(seq, None)
            self._closed = True
            raise self._lost_error()
        # the pump may already have resolved+removed it; re-check closed to
        # avoid an unresolvable future registered after pump exit
        if self._closed:
            with self._pending_lock:
                if self._pending.pop(seq, None) is not None:
                    fut.set(_RemoteError(self._lost_error()))
        return fut

    def push(self, method: str, **kwargs):
        if self._closed:
            raise self._lost_error()
        inj = _fi.ACTIVE
        plan = inj.on_send(method) if inj is not None else None
        if plan is not None:
            _fi.apply_send_plan(plan, self.close, method)
            if plan.drop:
                return   # injected loss: one-way messages vanish silently
        payload = pickle.dumps((method, kwargs),
                               protocol=pickle.HIGHEST_PROTOCOL)
        rc = self._lib.rpc_cl_send(self._h, _PUSH, 0, payload,
                                   len(payload), 0)
        if rc == 0 and plan is not None and plan.dup:
            rc = self._lib.rpc_cl_send(self._h, _PUSH, 0, payload,
                                       len(payload), 0)
        if rc != 0:
            self._closed = True
            raise self._lost_error()

    def push_parts(self, method: str, kwargs: dict, parts,
                   pool: str | None = None):
        """One-way out-of-band send (protocol.PyRpcClient.push_parts
        surface). rpc_cl_send takes one contiguous buffer, so the parts
        are assembled into a single preallocated bytearray — one copy,
        versus pickle-into-frame + frame concat on the legacy path."""
        if self._closed:
            raise self._lost_error()
        inj = _fi.ACTIVE
        plan = inj.on_send(method) if inj is not None else None
        if plan is not None:
            _fi.apply_send_plan(plan, self.close, method)
            if plan.drop:
                return   # injected loss: one-way messages vanish silently
        import struct

        head = pickle.dumps((method, kwargs, pool),
                            protocol=pickle.HIGHEST_PROTOCOL)
        views = [memoryview(p) for p in parts]
        total = 4 + len(head) + sum(v.nbytes for v in views)
        payload = bytearray(total)
        struct.pack_into(">I", payload, 0, len(head))
        payload[4:4 + len(head)] = head
        off = 4 + len(head)
        for v in views:
            payload[off:off + v.nbytes] = v
            off += v.nbytes
        buf = ctypes.cast((ctypes.c_char * total).from_buffer(payload),
                          ctypes.c_char_p)
        rc = self._lib.rpc_cl_send(self._h, _PUSH_OOB, 0, buf, total, 0)
        if rc == 0 and plan is not None and plan.dup:
            rc = self._lib.rpc_cl_send(self._h, _PUSH_OOB, 0, buf, total, 0)
        if rc != 0:
            self._closed = True
            raise self._lost_error()

    # ----------------------------------------------------------------- pump
    def _ensure_pump(self):
        if self._pump is None:
            with self._close_lock:
                if self._pump is None and not self._closed:
                    self._pump = threading.Thread(
                        target=self._pump_loop, daemon=True,
                        name=f"rpc-pump-{self.addr}")
                    self._pump.start()

    def _pump_loop(self):
        from ray_tpu._private.protocol import _RemoteError

        kind = ctypes.c_int()
        seq = ctypes.c_longlong()
        out = ctypes.c_void_p()
        out_len = ctypes.c_size_t()
        while True:
            rc = self._lib.rpc_cl_poll_async(
                self._h, -1, ctypes.byref(kind), ctypes.byref(seq),
                ctypes.byref(out), ctypes.byref(out_len))
            if rc == 2:
                break
            if rc != 0:
                continue
            data = _take_buf(self._lib, out, out_len.value)
            try:
                payload = pickle.loads(data)
            except Exception:
                continue
            if kind.value == _REPLY:
                with self._pending_lock:
                    fut = self._pending.pop(seq.value, None)
                if fut is not None:
                    fut.set(payload)
            elif kind.value == _PUSH and self._on_push is not None:
                try:
                    self._on_push(payload)
                except Exception:
                    pass
        self._closed = True
        err = _RemoteError(self._lost_error())
        with self._pending_lock:
            pending, self._pending = self._pending, {}
        for fut in pending.values():
            fut.set(err)

    @property
    def closed(self) -> bool:
        return self._closed or bool(self._lib.rpc_cl_closed(self._h))

    def close(self):
        # rpc_cl_close shuts the socket, joins the C reader, drains queued
        # buffers and notifies all waiters; the handle itself stays valid
        # forever (intentional ~bytes-sized leak) so racing call/wait
        # threads can never use-after-free — they just observe "closed".
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._lib.rpc_cl_close(self._h)
        pump = self._pump
        if pump is not None and pump is not threading.current_thread():
            pump.join(timeout=10.0)


class NativeConnection:
    """Server-side connection facade (protocol.Connection surface)."""

    def __init__(self, server: "NativeRpcServer", conn_id: int):
        self._server = server
        self._conn_id = conn_id
        self.id = f"native-{conn_id}"
        self.meta: dict = {}
        self.alive = True
        self.peer = ("native", conn_id)

    def push(self, method: str, **kwargs):
        payload = pickle.dumps((method, kwargs),
                               protocol=pickle.HIGHEST_PROTOCOL)
        rc = self._server._lib.rpc_sv_send(
            self._server._h, self._conn_id, _PUSH, 0, payload,
            len(payload))
        if rc != 0:
            self.alive = False

    def reply(self, seq: int, result):
        """Send a (possibly deferred) reply; pairs with NO_REPLY handlers."""
        from ray_tpu._private.protocol import _RemoteError

        try:
            blob = pickle.dumps(result, protocol=pickle.HIGHEST_PROTOCOL)
        except Exception as e:  # unpicklable result: report, don't hang
            blob = pickle.dumps(_RemoteError(RuntimeError(
                f"unpicklable rpc result: {e}")))
        rc = self._server._lib.rpc_sv_send(
            self._server._h, self._conn_id, _REPLY, seq, blob, len(blob))
        if rc != 0:
            self.alive = False


_NO_REPLY = object()


class NativeRpcServer:
    """protocol.PyRpcServer-compatible server over the C core.

    Dispatch policy matches the Python server: REQUESTs run on a fresh
    thread (handlers may block — long-polls, task execution); PUSHes run
    inline on the pump. Methods named in the handler's ``INLINE_RPC``
    set run inline too (must be non-blocking); an inline handler may
    return ``protocol.NO_REPLY`` and later answer via ``conn.reply``.
    """

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._lib = load_lib()
        self._h = self._lib.rpc_sv_start(host.encode(), port)
        if not self._h:
            raise OSError(f"cannot bind rpc server on {host}:{port}")
        self.addr = (host, self._lib.rpc_sv_port(self._h))
        self._conns: dict[int, NativeConnection] = {}
        self._stopped = False
        self._inline = getattr(handler, "INLINE_RPC", frozenset())
        self._deferred = getattr(handler, "DEFERRED_RPC", frozenset())
        self._pump = threading.Thread(
            target=self._pump_loop, daemon=True,
            name=f"rpc-sv-pump-{self.addr[1]}")

    def start(self):
        self._pump.start()
        return self

    def connections(self):
        return list(self._conns.values())

    def _lookup(self, method: str):
        from ray_tpu._private.protocol import RpcError

        fn = getattr(self._handler, f"rpc_{method}", None)
        if fn is None:
            raise RpcError(f"no such rpc method: {method}")
        return fn

    def _run_handler(self, conn, seq, method, kwargs):
        from ray_tpu._private.protocol import NO_REPLY, _RemoteError

        try:
            if method in self._deferred:
                result = self._lookup(method)(conn, seq, **kwargs)
            else:
                result = self._lookup(method)(conn, **kwargs)
        except BaseException as e:  # noqa: BLE001 — ship handler errors back
            result = _RemoteError(e)
        if result is NO_REPLY:
            return
        inj = _fi.ACTIVE
        if inj is not None:
            stall = inj.on_reply(method)
            if stall:
                time.sleep(stall)   # injected slow peer (GC pause analog)
        conn.reply(seq, result)

    def _pump_loop(self):
        conn_id = ctypes.c_ulonglong()
        kind = ctypes.c_int()
        seq = ctypes.c_longlong()
        out = ctypes.c_void_p()
        out_len = ctypes.c_size_t()
        while True:
            rc = self._lib.rpc_sv_next(
                self._h, -1, ctypes.byref(conn_id), ctypes.byref(kind),
                ctypes.byref(seq), ctypes.byref(out), ctypes.byref(out_len))
            if rc == 2:
                break
            if rc != 0:
                continue
            cid = conn_id.value
            if kind.value in (_EV_CONNECT, _EV_DISCONNECT):
                _take_buf(self._lib, out, out_len.value)  # 1-byte event buf
            if kind.value == _EV_CONNECT:
                conn = NativeConnection(self, cid)
                self._conns[cid] = conn
                cb = getattr(self._handler, "on_connect", None)
                if cb is not None:
                    try:
                        cb(conn)
                    except Exception:
                        pass
                continue
            if kind.value == _EV_DISCONNECT:
                conn = self._conns.pop(cid, None)
                if conn is not None:
                    conn.alive = False
                    cb = getattr(self._handler, "on_disconnect", None)
                    if cb is not None:
                        try:
                            cb(conn)
                        except Exception:
                            pass
                continue
            conn = self._conns.get(cid)
            if conn is None:
                _take_buf(self._lib, out, out_len.value)
                continue
            if kind.value == _PUSH_OOB:
                # zero-copy hand-off: the handler's frame views the C
                # reader's malloc'd buffer in place (no string_at copy
                # of the tensor body); frame.release() frees it
                frame = _NativeOobFrame(self._lib, out.value,
                                        out_len.value)
                try:
                    method, kwargs = frame.parse_head()
                    self._lookup(method)(conn, frame=frame, **kwargs)
                except Exception:
                    frame.release()
                continue
            data = _take_buf(self._lib, out, out_len.value)
            try:
                method, kwargs = pickle.loads(data)
            except Exception:
                continue
            if kind.value == _PUSH:
                try:
                    self._lookup(method)(conn, **kwargs)
                except Exception:
                    pass
            elif kind.value == _REQUEST:
                if method in self._inline:
                    self._run_handler(conn, seq.value, method, kwargs)
                else:
                    threading.Thread(
                        target=self._run_handler,
                        args=(conn, seq.value, method, kwargs),
                        daemon=True).start()

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self._lib.rpc_sv_stop(self._h)
        if self._pump.is_alive() and \
                threading.current_thread() is not self._pump:
            self._pump.join(timeout=5.0)
        for conn in list(self._conns.values()):
            conn.alive = False
        self._conns.clear()
