"""Build-on-demand for the native (C++) runtime components.

The reference ships its native core prebuilt via bazel; here the store
library is compiled once per checkout with g++ and cached under build/.
Rebuilds happen automatically when the source is newer than the .so.
"""
from __future__ import annotations

import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LOCK = threading.Lock()

_LIBS = {
    "raystore": ["src/store/store.cc", "src/store/data_server.cc"],
    "rayrpc": ["src/rpc/rpc_core.cc"],
    "rayquant": ["src/quant/quant.cc"],
}

# Per-lib extra flag sets, tried in order until one compiles. The quant
# kernels are pure elementwise/reduction loops whose whole value is
# vectorization: -march=native roughly triples their throughput on AVX2
# hosts, and because every checkout compiles its own .so on demand the
# binary never travels to a different machine. The plain -O3 fallback
# keeps exotic toolchains working (slower, still correct).
# -ffp-contract=off is a CORRECTNESS flag, not tuning: the fused
# add-both kernel must stay mul+mul+add so deq(a)+deq(b) is
# bit-commutative — an FMA contraction would round rank 0's and
# rank 1's sums differently and break the collective's
# rank-identical-results property (and drift from the numpy fallback).
_EXTRA_FLAGS = {
    "rayquant": (["-O3", "-march=native", "-ffp-contract=off"],
                 ["-O3", "-ffp-contract=off"]),
}


def lib_path(name: str) -> str:
    return os.path.join(_BUILD_DIR, f"lib{name}.so")


def ensure_lib(name: str) -> str:
    """Compile lib<name>.so if missing or stale; return its path."""
    sources = [os.path.join(_REPO_ROOT, s) for s in _LIBS[name]]
    out = lib_path(name)
    with _LOCK:
        if os.path.exists(out):
            newest_src = max(os.path.getmtime(s) for s in sources)
            if os.path.getmtime(out) >= newest_src:
                return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = out + f".tmp.{os.getpid()}"
        last_err = None
        for extra in _EXTRA_FLAGS.get(name, (["-O2"],)):
            cmd = [
                "g++", *extra, "-std=c++17", "-shared", "-fPIC",
                "-o", tmp, *sources, "-lpthread", "-lrt",
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True)
            except subprocess.CalledProcessError as e:
                last_err = e
                continue
            os.replace(tmp, out)
            return out
        raise last_err
    return out
