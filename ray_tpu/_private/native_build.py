"""Build-on-demand for the native (C++) runtime components.

The reference ships its native core prebuilt via bazel; here the store
library is compiled once per checkout with g++ and cached under build/.
Rebuilds happen automatically when the source is newer than the .so.
"""
from __future__ import annotations

import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LOCK = threading.Lock()

_LIBS = {
    "raystore": ["src/store/store.cc", "src/store/data_server.cc"],
    "rayrpc": ["src/rpc/rpc_core.cc"],
}


def lib_path(name: str) -> str:
    return os.path.join(_BUILD_DIR, f"lib{name}.so")


def ensure_lib(name: str) -> str:
    """Compile lib<name>.so if missing or stale; return its path."""
    sources = [os.path.join(_REPO_ROOT, s) for s in _LIBS[name]]
    out = lib_path(name)
    with _LOCK:
        if os.path.exists(out):
            newest_src = max(os.path.getmtime(s) for s in sources)
            if os.path.getmtime(out) >= newest_src:
                return out
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = out + f".tmp.{os.getpid()}"
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC",
            "-o", tmp, *sources, "-lpthread", "-lrt",
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    return out
