"""Binary ID types for every entity in the system.

TPU-native analog of the reference's ID substrate
(/root/reference/src/ray/common/id.h, id_def.h): fixed-width random binary
IDs with hex rendering and structured derivation (task IDs embed the job,
object IDs embed the producing task + return index), so ownership and
lineage can be recovered from an ID alone.
"""
from __future__ import annotations

import hashlib
import os

_NIL = b""


class BaseID:
    SIZE = 16
    __slots__ = ("_bin",)

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} must be {self.SIZE} bytes, got {len(binary)}"
            )
        self._bin = binary

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bin == b"\xff" * self.SIZE

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bin))

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"


class JobID(BaseID):
    SIZE = 4

    @classmethod
    def from_int(cls, i: int):
        return cls(i.to_bytes(4, "little"))

    def int(self) -> int:
        return int.from_bytes(self._bin, "little")


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    """12 bytes: 8 random + 4 job id (mirrors reference layout: unique part
    + job part)."""

    SIZE = 12

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(8) + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bin[8:])


class TaskID(BaseID):
    """14 bytes: 10 unique + 4 job."""

    SIZE = 14

    @classmethod
    def of(cls, job_id: JobID):
        return cls(os.urandom(10) + job_id.binary())

    @classmethod
    def for_actor_task(cls, job_id: JobID, actor_id: ActorID, seq_no: int):
        h = hashlib.sha1(actor_id.binary() + seq_no.to_bytes(8, "little")).digest()
        return cls(h[:10] + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bin[10:])


class ObjectID(BaseID):
    """16 bytes: task id (14) + return/put index (2), so the producing task
    is recoverable — the basis of lineage reconstruction
    (reference: object_recovery_manager.h:30)."""

    SIZE = 16

    @classmethod
    def for_return(cls, task_id: TaskID, index: int):
        return cls(task_id.binary() + index.to_bytes(2, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int):
        # Put objects use the high half of the index space.
        return cls(task_id.binary() + (0x8000 | put_index).to_bytes(2, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bin[:14])

    def return_index(self) -> int:
        return int.from_bytes(self._bin[14:], "little") & 0x7FFF

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._bin[14:], "little") & 0x8000)


class PlacementGroupID(BaseID):
    SIZE = 16


ObjectRefID = ObjectID  # alias
